"""GSPMD multi-chip execution: shard the node axis, let XLA place collectives.

The scaling recipe: pad node and edge axes to multiples of the mesh size,
annotate every state/topology array with a :class:`NamedSharding` over the
``nodes`` mesh axis, and run the *same* round kernel under ``jit`` —
computation follows data, and XLA's SPMD partitioner inserts the
all-to-all/collective traffic for the only cross-shard operation the round
has: scattering outgoing messages through the ``rev`` permutation into
receiver ring-buffer slots (the ICI-riding replacement for the reference's
SimGrid mailbox delivery).  An explicitly scheduled ``shard_map`` halo
kernel lives in :mod:`flow_updating_tpu.parallel.sharded` for comparison.

Padding invariants: dummy edges attach to a guaranteed-*padded* node (never
a real one), and padded nodes are born dead (``alive=False``), so they can
never fire and no dummy traffic exists; padded values are zero so mass-type
metrics are unaffected.  Metrics must slice ``[:n_real]``.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState, init_state
from flow_updating_tpu.parallel.mesh import NODE_AXIS
from flow_updating_tpu.topology.graph import Topology

P = jax.sharding.PartitionSpec


def _ceil_to(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def pad_topology(topo: Topology, num_shards: int) -> tuple[Topology, int, int]:
    """Pad nodes/edges to multiples of ``num_shards``.

    Returns (padded_topology, n_real, e_real).  Always pads at least one
    node so dummy edges can attach to a padded (never-firing) node.
    """
    topo._require_edges("pad_topology (edge-kernel sharding)")
    N, E = topo.num_nodes, topo.num_edges
    Np = _ceil_to(N + 1, num_shards)
    Ep = _ceil_to(E, num_shards)
    pad_n = Np - N
    pad_e = Ep - E
    dummy = Np - 1  # a padded node by construction

    src = np.concatenate([topo.src, np.full(pad_e, dummy, np.int32)])
    dst = np.concatenate([topo.dst, np.full(pad_e, dummy, np.int32)])
    rev = np.concatenate(
        [topo.rev, np.arange(E, Ep, dtype=np.int32)]  # dummies reverse to self
    )
    edge_rank = np.concatenate(
        [topo.edge_rank, np.arange(pad_e, dtype=np.int32)]
    )
    delay = np.concatenate([topo.delay, np.ones(pad_e, np.int32)])
    out_deg = np.concatenate([topo.out_deg, np.zeros(pad_n, np.int32)])
    values = np.concatenate([topo.values, np.zeros(pad_n)])
    # CSR over the padded edge list (dummy edges form node `dummy`'s row) —
    # used only for segment-end lookups, not for degree arithmetic.
    counts = np.bincount(src, minlength=Np)
    row_start = np.zeros(Np + 1, dtype=np.int64)
    np.cumsum(counts, out=row_start[1:])

    padded = dataclasses.replace(
        topo,
        num_nodes=Np,
        src=src,
        dst=dst,
        rev=rev,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=delay,
        values=values,
        names=None,
        speeds=None,
        bandwidth=None,
        latency_s=None,
        # the link-contention model is single-device (engine rejects
        # contention+mesh); dropping the arrays keeps the padded pytree
        # consistent with topo_sharding's field set
        edge_links=None,
        link_ser_rounds=None,
        link_shared=None,
        lat_rounds=None,
        # a structure descriptor describes the UNpadded node set; carrying
        # it through would only trip _init_structured's n-check downstream
        structure=None,
    )
    return padded, N, E


def state_sharding(mesh: jax.sharding.Mesh) -> FlowUpdatingState:
    """Pytree of NamedShardings matching FlowUpdatingState: node and edge
    arrays split over the node axis, ring buffers split on their edge axis,
    scalars replicated."""
    ns = lambda spec: jax.sharding.NamedSharding(mesh, spec)
    ax = P(NODE_AXIS)
    return FlowUpdatingState(
        t=ns(P()),
        value=ns(ax),
        flow=ns(ax),
        est=ns(ax),
        recv=ns(ax),
        ticks=ns(ax),
        stamp=ns(ax),
        last_avg=ns(ax),
        fired=ns(ax),
        alive=ns(ax),
        edge_ok=ns(ax),
        pending_flow=ns(P(None, NODE_AXIS)),
        pending_est=ns(P(None, NODE_AXIS)),
        pending_valid=ns(P(None, NODE_AXIS)),
        pending_stamp=ns(P(None, NODE_AXIS)),
        buf_flow=ns(P(None, NODE_AXIS)),
        buf_est=ns(P(None, NODE_AXIS)),
        buf_valid=ns(P(None, NODE_AXIS)),
        key=ns(P()),
    )


def topo_sharding(mesh: jax.sharding.Mesh, arrays):
    """Shardings for TopoArrays: edge/node arrays split, row_start
    replicated (N+1 is never divisible; it is only gathered from)."""
    ns = lambda spec: jax.sharding.NamedSharding(mesh, spec)
    ax = P(NODE_AXIS)
    return type(arrays)(
        src=ns(ax),
        dst=ns(ax),
        rev=ns(ax),
        out_deg=ns(ax),
        row_start=ns(P()),
        edge_rank=ns(ax),
        delay=ns(ax),
        edge_color=None if arrays.edge_color is None else ns(ax),
        num_colors=arrays.num_colors,
    )


def init_sharded_state(
    padded: Topology, cfg: RoundConfig, n_real: int,
    mesh: jax.sharding.Mesh, seed: int = 0,
):
    """Fresh state on the mesh: padded nodes are dead, all arrays placed
    with their NamedShardings.  Returns (state, topo_arrays)."""
    state = init_state(padded, cfg, seed=seed)
    alive = state.alive.at[n_real:].set(False)
    state = state.replace(alive=alive)
    arrays = padded.device_arrays(coloring=cfg.needs_coloring)
    state = shard_state(state, mesh)
    arrays = jax.device_put(arrays, topo_sharding(mesh, arrays))
    return state, arrays


def shard_state(state: FlowUpdatingState, mesh: jax.sharding.Mesh):
    return jax.device_put(state, state_sharding(mesh))
