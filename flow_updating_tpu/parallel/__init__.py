from flow_updating_tpu.parallel.mesh import make_mesh
from flow_updating_tpu.parallel.structured_sharded import (
    PodShardedFatTreeKernel,
)
from flow_updating_tpu.parallel.auto import (
    pad_topology,
    init_sharded_state,
    shard_state,
    state_sharding,
    topo_sharding,
)

__all__ = [
    "make_mesh",
    "PodShardedFatTreeKernel",
    "pad_topology",
    "init_sharded_state",
    "shard_state",
    "state_sharding",
    "topo_sharding",
]
