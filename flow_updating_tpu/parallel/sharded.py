"""Explicitly scheduled multi-chip execution: ``shard_map`` + halo exchange.

The GSPMD path (:mod:`flow_updating_tpu.parallel.auto`) hands XLA globally
annotated arrays and lets the SPMD partitioner place collectives.  This
module is the hand-scheduled alternative — the TPU-native analogue of the
reference's point-to-point mailbox delivery across hosts (SimGrid's
rendezvous matching, SURVEY.md N4), done the way a multi-pod gossip system
would actually run:

* nodes are partitioned into contiguous blocks, one block per device; every
  directed edge lives with its *source* node's shard, so segment reductions
  and firing decisions are purely local;
* the only cross-device traffic is message delivery on *cut* edges (edges
  whose reverse lives on another shard).  Those are compiled into a fixed
  per-shard halo send list at plan time; each round the payloads (flow,
  estimate, valid) are exchanged with ``lax.all_gather`` over the mesh axis
  (ICI) and scattered into the receiver's ring-buffer slot.  The routing
  tables (target shard/slot/delay per halo entry) are plan-time constants,
  replicated once — never re-communicated;
* intra-shard edges deliver with a local scatter, exactly like the
  single-device kernel.

The per-round collective volume is ``S * H * (2 floats + 1 bool)`` (H = max
cut edges per shard) — independent of the number of intra-shard edges, so a
community-structured partition keeps ICI traffic tiny.

The round math itself is shared with the single-device kernel
(:func:`flow_updating_tpu.models.rounds.deliver_phase` /
:func:`~flow_updating_tpu.models.rounds.fire_core` run unchanged on local
shard views); only message *delivery* differs.  The fast synchronous
pairwise mode has its own round body (:func:`_local_round_fastpair`): its
direct two-sided exchange needs the remote endpoint's *current estimate*,
so for cut edges that value (not a message payload) rides the same halo
machinery; build the plan with ``plan_sharding(..., coloring=True)``.
"""

from __future__ import annotations

import dataclasses
import functools
import math

from flow_updating_tpu.utils import struct
import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import (
    FlowUpdatingState,
    _ex,
    check_payload_values,
)
from flow_updating_tpu.models.rounds import deliver_phase, fire_core
from flow_updating_tpu.parallel.mesh import NODE_AXIS, shard_map
from flow_updating_tpu.topology.graph import Topology, TopoArrays

P = jax.sharding.PartitionSpec

#: public cut-edge exchange modes.  'ppermute' and 'allgather' are the
#: serialized oracles; 'overlap' is the interior/frontier-split schedule
#: (ppermute wire, async-overlappable — parallel/overlap.py) and
#: 'overlap_pallas' its Pallas remote-DMA form (ops/pallas_halo.py).
HALO_MODES = ("ppermute", "allgather", "overlap", "overlap_pallas")

#: plus the profiling-only interior probe (overlap schedule with the
#: exchange elided — obs/profile.overlap_report's timing baseline) and
#: the fat-frontier resolution of 'overlap' (overlap.resolve_mode)
_HALO_MODES_INTERNAL = HALO_MODES + ("interior", "overlap_full")


def _check_halo(halo: str, *, _internal: bool = False) -> None:
    if halo in (_HALO_MODES_INTERNAL if _internal else HALO_MODES):
        return
    if halo in _HALO_MODES_INTERNAL:
        raise ValueError(
            f"halo={halo!r} is internal-only (the profiling probe / a "
            f"plan-time schedule resolution), not a correct protocol "
            f"mode: use one of {HALO_MODES}")
    raise ValueError(
        f"unknown halo mode {halo!r}: use one of {HALO_MODES}")


@struct.dataclass
class PlanArrays:
    """Per-shard device arrays, stacked on a leading shard axis (S, ...)."""

    src_local: jnp.ndarray   # (S, Eb) i32 — local source node of each edge slot
    out_deg: jnp.ndarray     # (S, Nb) i32 — real out-degree per local node
    row_start: jnp.ndarray   # (S, Nb+1) i32 — local CSR offsets
    edge_rank: jnp.ndarray   # (S, Eb) i32 — rank within local src row
    delay: jnp.ndarray       # (S, Eb) i32 — delivery delay in rounds
    tshard: jnp.ndarray      # (S, Eb) i32 — shard owning rev(edge)
    tlocal: jnp.ndarray      # (S, Eb) i32 — rev(edge)'s slot there (Eb = none)
    halo_idx: jnp.ndarray    # (S, H) i32 — slots of cut edges (Eb = padding)
    edge_color: jnp.ndarray | None = None  # (S, Eb) i32, -1 on padding
    #                          (present iff the plan was built with
    #                           coloring=True — fast synchronous pairwise)


@struct.dataclass
class HaloTables:
    """Replicated plan-time routing tables for halo entries, in all_gather
    (shard-major) order.  Constant across rounds — kept out of the per-round
    collective entirely."""

    tshard: jnp.ndarray  # (S*H,) i32 — receiving shard (-1 = padding)
    tlocal: jnp.ndarray  # (S*H,) i32 — slot there (Eb = padding)
    delay: jnp.ndarray   # (S*H,) i32 — sending edge's delivery delay


@struct.dataclass
class PermTables:
    """Per-offset point-to-point halo routing (``halo='ppermute'``).

    One entry per nonzero shard offset ``d`` that carries any cut edge:
    shard ``s`` sends its cut edges targeting shard ``(s+d) % S`` as one
    ``ppermute`` of a dense payload block.  All tables are plan-time
    constants sharded with their rows; per-round traffic is exactly the
    padded per-pair cut-edge payloads — O(cut edges), not O(S * cut).
    """

    send_idx: tuple      # per offset: (S, Hd) i32 local slots to send (Eb pad)
    recv_tlocal: tuple   # per offset: (S, Hd) i32 receiver slot (Eb pad)
    recv_delay: tuple    # per offset: (S, Hd) i32 sending edge's delay


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Host-side sharding plan for one topology on S devices."""

    topo: Topology
    num_shards: int
    cap: int            # real nodes per shard (last shard may be short)
    Nb: int             # local node count incl. the per-shard dummy (cap + 1)
    Eb: int             # padded edge slots per shard
    H: int              # padded halo (cut-edge) slots per shard
    arrays: PlanArrays  # numpy-backed; device_put at init
    halo: HaloTables    # numpy-backed, replicated at init
    values: np.ndarray  # (S, Nb) initial node values (0 on padding)
    alive0: np.ndarray  # (S, Nb) bool initial liveness (False on padding)
    perm_offsets: tuple = ()         # nonzero shard offsets with cut edges
    perm_tables: PermTables | None = None  # per-offset ppermute routing
    num_colors: int = 0              # >0 iff built with coloring=True
    order: np.ndarray | None = None  # partition node order (new -> original
    #                                  id); None = identity (contiguous ids)
    edge_shard: np.ndarray | None = None  # (E,) owner shard per (possibly
    edge_slot: np.ndarray | None = None   # reordered) global edge + slot —
    #                                  the blocked-layout <-> global edge
    #                                  bijection (checkpoint gather/scatter)

    @property
    def cut_fraction(self) -> float:
        """Fraction of directed edges whose delivery crosses shards."""
        idx = np.asarray(self.arrays.halo_idx)
        return float((idx < self.Eb).sum()) / max(self.topo.num_edges, 1)

    def collective_bytes_per_round(self, dtype_bytes: int = 4) -> dict:
        """Per-round halo traffic entering the interconnect, both paths,
        using each path's ACTUAL wire format.

        ``allgather``: every shard broadcasts its padded cut-edge payload
        block (flow + estimate arrays of the ledger dtype, plus a separate
        1-byte bool valid array) to all S shards — S * S * H entries.
        The full-width broadcast is load-bearing: it is the single-
        collective oracle (simplest possible wire, every receiver sees
        everything), and the row-subset alternatives ARE the ppermute /
        overlap modes; tests/test_parallel.py pins this accounting
        against the compiled program's actual HLO collective bytes so
        the two can never silently diverge.
        ``ppermute``: each shard sends each per-offset padded block to
        exactly one peer — S * sum(Hd) entries, each 3 lanes of the ledger
        dtype (valid travels as a dtype lane in the stacked payload).
        ``overlap``/``overlap_pallas`` put exactly the ppermute payloads
        on the wire (same blocks, earlier in the schedule), so their
        byte count is reported under the same key.
        """
        S, H = self.num_shards, self.H
        ag_entry = 2 * dtype_bytes + 1   # flow + est + bool valid
        pp_entry = 3 * dtype_bytes      # jnp.stack([flow, est, valid.astype])
        sum_hd = sum(
            int(np.asarray(t).shape[1]) for t in (
                self.perm_tables.send_idx if self.perm_tables else ())
        )
        pp = S * sum_hd * pp_entry
        return {
            "allgather_bytes": S * S * H * ag_entry,
            "ppermute_bytes": pp,
            "overlap_bytes": pp,   # identical wire, overlapped schedule
            "cut_edges": int((np.asarray(self.arrays.halo_idx)
                              < self.Eb).sum()),
            "cut_fraction": round(self.cut_fraction, 4),
            "num_offsets": len(self.perm_offsets),
        }


def plan_sharding(topo: Topology, num_shards: int,
                  partition: str = "contiguous",
                  coloring: bool = False) -> ShardPlan:
    """Partition nodes into contiguous blocks and edges with their source.

    ``partition='bfs'`` renumbers nodes by BFS order first
    (:func:`~flow_updating_tpu.topology.graph.locality_order`), which keeps
    neighborhoods within blocks and cuts far fewer edges on structured
    topologies; estimates read back through :func:`gather_estimates` are
    always in the caller's original node order.

    Local node ``Nb-1`` of every shard is a dummy (dead, value 0) that owns
    the padded edge slots, so padding can never fire or send.
    """
    topo._require_edges("plan_sharding (halo-exchange partitioning)")
    if coloring:
        # compute (and cache) on the ORIGINAL topology BEFORE any reorder;
        # reorder_topology carries the cache through, so the sharded run
        # fires the exact matching sequence of the single-device kernel
        topo.edge_coloring()
    order = None
    if partition == "bfs":
        from flow_updating_tpu.topology.graph import (
            locality_order, reorder_topology,
        )

        order = locality_order(topo)
        topo = reorder_topology(topo, order)
    elif partition != "contiguous":
        raise ValueError(f"unknown partition {partition!r}")
    N, E, S = topo.num_nodes, topo.num_edges, num_shards
    cap = max(1, math.ceil(N / S))
    Nb = cap + 1
    shard_of = topo.src.astype(np.int64) // cap
    local_of = topo.src.astype(np.int64) % cap

    counts = np.bincount(shard_of, minlength=S)
    Eb = max(int(counts.max()) if E else 0, 1)
    # position of each edge within its shard (edges are (src, dst)-sorted, so
    # per-shard order stays sorted by local (src, dst))
    starts = np.zeros(S + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(E, dtype=np.int64) - starts[shard_of]

    owner_shard = shard_of            # per global edge
    owner_pos = pos
    rev_shard = owner_shard[topo.rev]
    rev_pos = owner_pos[topo.rev]

    src_local = np.full((S, Eb), Nb - 1, np.int32)
    delay = np.ones((S, Eb), np.int32)
    tshard = np.tile(
        np.arange(S, dtype=np.int32).reshape(S, 1), (1, Eb)
    )
    tlocal = np.full((S, Eb), Eb, np.int32)

    src_local[owner_shard, owner_pos] = local_of
    delay[owner_shard, owner_pos] = topo.delay
    tshard[owner_shard, owner_pos] = rev_shard
    tlocal[owner_shard, owner_pos] = rev_pos

    edge_color = None
    num_colors = 0
    if coloring:
        col, num_colors = topo.edge_coloring()
        edge_color = np.full((S, Eb), -1, np.int32)
        edge_color[owner_shard, owner_pos] = col

    # local CSR (padded slots all belong to the dummy row at the end)
    out_deg = np.zeros((S, Nb), np.int32)
    np.add.at(out_deg, (owner_shard, local_of), 1)
    row_start = np.zeros((S, Nb + 1), np.int32)
    full_deg = out_deg.copy()
    full_deg[:, Nb - 1] += Eb - counts.astype(np.int32)
    np.cumsum(full_deg, axis=1, out=row_start[:, 1:])
    slot_idx = np.tile(np.arange(Eb, dtype=np.int64), (S, 1))
    edge_rank = (slot_idx - row_start[np.arange(S)[:, None], src_local]).astype(
        np.int32
    )

    # halo send lists: cut-edge slots, padded with the Eb sentinel
    is_cut = (tshard != np.arange(S, dtype=np.int32).reshape(S, 1)) & (
        tlocal < Eb
    )
    H = max(int(is_cut.sum(axis=1).max()), 1)
    halo_idx = np.full((S, H), Eb, np.int32)
    for s in range(S):
        slots = np.where(is_cut[s])[0]
        halo_idx[s, : len(slots)] = slots

    vals_flat = np.zeros(S * cap, np.float64)
    vals_flat[:N] = topo.values
    alive_flat = np.zeros(S * cap, bool)
    alive_flat[:N] = True
    values = np.zeros((S, Nb), np.float64)
    values[:, :cap] = vals_flat.reshape(S, cap)
    alive0 = np.zeros((S, Nb), bool)
    alive0[:, :cap] = alive_flat.reshape(S, cap)

    # replicated routing tables in all_gather (shard-major) order
    hi = np.minimum(halo_idx, Eb - 1)
    h_ok = halo_idx < Eb
    sidx = np.arange(S)[:, None]
    halo = HaloTables(
        tshard=np.where(h_ok, tshard[sidx, hi], -1).astype(np.int32).ravel(),
        tlocal=np.where(h_ok, tlocal[sidx, hi], Eb).astype(np.int32).ravel(),
        delay=np.where(h_ok, delay[sidx, hi], 1).astype(np.int32).ravel(),
    )

    # point-to-point routing: group each shard's cut edges by target-shard
    # OFFSET (d = target - source mod S); one ppermute per distinct offset
    off_of_cut = np.where(
        is_cut, (tshard - np.arange(S, dtype=np.int32)[:, None]) % S, -1
    )
    offsets = sorted(int(d) for d in np.unique(off_of_cut) if d > 0)
    send_idx_t, recv_tlocal_t, recv_delay_t = [], [], []
    for d in offsets:
        per_shard = [np.where(off_of_cut[s] == d)[0] for s in range(S)]
        Hd = max(max((len(p) for p in per_shard), default=0), 1)
        sidx_d = np.full((S, Hd), Eb, np.int32)
        for s in range(S):
            sidx_d[s, : len(per_shard[s])] = per_shard[s]
        # receiver-side tables: shard r's row describes what arrives from
        # shard (r - d) % S, in that sender's send order
        rt = np.full((S, Hd), Eb, np.int32)
        rd = np.ones((S, Hd), np.int32)
        for r in range(S):
            s = (r - d) % S
            slots = per_shard[s]
            rt[r, : len(slots)] = tlocal[s, slots]
            rd[r, : len(slots)] = delay[s, slots]
        send_idx_t.append(sidx_d)
        recv_tlocal_t.append(rt)
        recv_delay_t.append(rd)
    perm_tables = PermTables(
        send_idx=tuple(send_idx_t),
        recv_tlocal=tuple(recv_tlocal_t),
        recv_delay=tuple(recv_delay_t),
    )

    arrays = PlanArrays(
        src_local=src_local,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=delay,
        tshard=tshard,
        tlocal=tlocal,
        halo_idx=halo_idx,
        edge_color=edge_color,
    )
    return ShardPlan(
        topo=topo, num_shards=S, cap=cap, Nb=Nb, Eb=Eb, H=H, arrays=arrays,
        halo=halo, values=values, alive0=alive0,
        perm_offsets=tuple(offsets), perm_tables=perm_tables, order=order,
        num_colors=num_colors,
        edge_shard=owner_shard.astype(np.int32),
        edge_slot=owner_pos.astype(np.int32),
    )


def _spec(x) -> P:
    return P(NODE_AXIS, *([None] * (np.ndim(x) - 1)))


def _feature_shards(mesh) -> int:
    """Size of the mesh's feature axis (1 when absent): the 2-D
    ``('nodes', 'feature')`` mesh composes halo sharding with payload
    model-parallelism (parallel/feature.py)."""
    from flow_updating_tpu.parallel.mesh import FEATURE_AXIS

    if FEATURE_AXIS in getattr(mesh, "axis_names", ()):
        return int(mesh.shape[FEATURE_AXIS])
    return 1


def _state_specs(state, mesh):
    """Halo state specs.  Under a 2-D ``('nodes', 'feature')`` mesh a
    VECTOR state's payload leaves additionally shard their trailing
    feature axis — the D lanes are independent protocol instances, so
    each (node-shard, feature-shard) device runs the unmodified local
    round on its ``(Nb, D/S_f)`` block and the node-axis collectives
    move ``D/S_f`` lanes per cut edge.  Control leaves (and every leaf
    of a scalar state) keep the 1-D node spec."""
    from flow_updating_tpu.parallel.mesh import FEATURE_AXIS

    if _feature_shards(mesh) == 1 or state.value.ndim != 3:
        return jax.tree.map(_spec, state)
    from flow_updating_tpu.parallel.feature import PAYLOAD_LEAVES

    specs = {}
    for f in state.__dataclass_fields__:
        x = getattr(state, f)
        if f in PAYLOAD_LEAVES:
            specs[f] = P(NODE_AXIS, *([None] * (np.ndim(x) - 2)),
                         FEATURE_AXIS)
        else:
            specs[f] = _spec(x)
    return state.replace(**specs)


def _sharding_tree(tree, mesh):
    return jax.tree.map(
        lambda x: jax.sharding.NamedSharding(mesh, _spec(x)), tree
    )


def init_plan_state(
    plan: ShardPlan, cfg: RoundConfig, mesh: jax.sharding.Mesh,
    seed: int = 0, values=None,
) -> FlowUpdatingState:
    """Fresh sharded state: every leaf carries a leading (S,) shard axis and
    is placed with its block on its device.

    ``values`` overrides the plan's node values and may be ``(N, D)`` in
    the caller's ORIGINAL node order (vector payloads): payload arrays
    then carry the trailing feature axis, co-sharded with their node/edge
    blocks (the feature axis itself is never split — it travels with its
    node)."""
    if cfg.needs_coloring and plan.num_colors == 0:
        raise ValueError(
            "fast synchronous pairwise needs the edge coloring in the "
            "plan: build it with plan_sharding(..., coloring=True)"
        )
    S, Nb, Eb, D = plan.num_shards, plan.Nb, plan.Eb, cfg.delay_depth
    dt = cfg.jnp_dtype
    if values is None:
        vals = plan.values
        F = ()
    else:
        values = np.asarray(values, np.float64)
        N = plan.topo.num_nodes
        check_payload_values(values, N)
        F = tuple(values.shape[1:])
        # original order -> partition order -> (S, Nb) blocks (same
        # layout rule as plan_sharding's scalar values)
        ordered = values[plan.order] if plan.order is not None else values
        flat = np.zeros((S * plan.cap,) + F, np.float64)
        flat[:N] = ordered
        vals = np.zeros((S, Nb) + F, np.float64)
        vals[:, : plan.cap] = flat.reshape((S, plan.cap) + F)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(seed), i))(
        jnp.arange(S)
    )
    state = FlowUpdatingState(
        t=jnp.zeros((S,), jnp.int32),
        value=jnp.asarray(vals, dt),
        flow=jnp.zeros((S, Eb) + F, dt),
        est=jnp.zeros((S, Eb) + F, dt),
        recv=jnp.zeros((S, Eb), bool),
        ticks=jnp.zeros((S, Nb), jnp.int32),
        stamp=jnp.zeros((S, Eb), jnp.int32),
        last_avg=jnp.zeros((S, Nb) + F, dt),
        fired=jnp.zeros((S, Nb), jnp.int32),
        alive=jnp.asarray(plan.alive0),
        edge_ok=jnp.ones((S, Eb), bool),
        pending_flow=jnp.zeros((S, cfg.pending_depth, Eb) + F, dt),
        pending_est=jnp.zeros((S, cfg.pending_depth, Eb) + F, dt),
        pending_valid=jnp.zeros((S, cfg.pending_depth, Eb), bool),
        pending_stamp=jnp.zeros((S, cfg.pending_depth, Eb), jnp.int32),
        buf_flow=jnp.zeros((S, D, Eb) + F, dt),
        buf_est=jnp.zeros((S, D, Eb) + F, dt),
        buf_valid=jnp.zeros((S, D, Eb), bool),
        key=keys,
    )
    if _feature_shards(mesh) > 1 and F:
        if F[0] % _feature_shards(mesh):
            raise ValueError(
                f"payload features D={F[0]} must divide evenly over "
                f"{_feature_shards(mesh)} feature shards")
        specs = _state_specs(state, mesh)
        shardings = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        return jax.device_put(state, shardings)
    return jax.device_put(state, _sharding_tree(state, mesh))


def _overlap_device_tables(plan: ShardPlan, mesh):
    """The overlap schedule's frontier-split tables, device-placed like
    the other per-shard plan arrays."""
    from flow_updating_tpu.parallel import overlap as _overlap

    ov = jax.tree.map(jnp.asarray, _overlap.build_overlap(plan))
    return jax.device_put(ov, _sharding_tree(ov, mesh))


def plan_device_arrays(
    plan: ShardPlan, mesh: jax.sharding.Mesh, halo: str | None = None
):
    """Device placement: per-shard arrays (incl. the per-offset ppermute
    tables) blocked over the mesh, all_gather routing tables replicated.
    Returns ``(PlanArrays, HaloTables, PermTables, OverlapTables)``;
    the overlap split tables are an O(S*Eb) host construction the
    serialized modes never read, so they are built only when ``halo``
    is an overlap mode (or None = mode unknown).  The round-program
    entry points rebuild them lazily if an overlap dispatch meets a
    tuple built without them."""
    from flow_updating_tpu.parallel import overlap as _overlap

    arrays = jax.tree.map(jnp.asarray, plan.arrays)
    arrays = jax.device_put(arrays, _sharding_tree(arrays, mesh))
    rep = jax.sharding.NamedSharding(mesh, P())
    halo_t = jax.device_put(jax.tree.map(jnp.asarray, plan.halo), rep)
    perm = jax.tree.map(jnp.asarray, plan.perm_tables)
    perm = jax.device_put(perm, _sharding_tree(perm, mesh))
    ov = (_overlap_device_tables(plan, mesh)
          if halo is None or halo in _overlap.OVERLAP_MODES else None)
    return arrays, halo_t, perm, ov


def _lanes(x):
    """Payload -> lane-major for collectives: (H,) -> (1, H); a vector
    payload's (H, F) -> (F, H), so features ride the SAME ppermute /
    all_gather as extra lanes of one message block."""
    return x.T if x.ndim > 1 else x[None]


def _unlanes(m, ref):
    """Inverse of :func:`_lanes`, shaped like payload ``ref``."""
    return m.T if ref.ndim > 1 else m[0]


def _local_topo(pl: PlanArrays) -> TopoArrays:
    """One shard's block as the TopoArrays view the round math consumes
    (``dst``/``rev`` are placeholders: no local path reads dst, and
    delivery goes through tshard/tlocal).  Shared by the serialized
    bodies and the overlap schedule (parallel/overlap.py) so the local
    topology convention cannot drift between them."""
    return TopoArrays(
        src=pl.src_local,
        dst=pl.src_local,
        rev=pl.tlocal,
        out_deg=pl.out_deg,
        row_start=pl.row_start,
        edge_rank=pl.edge_rank,
        delay=pl.delay,
    )


def _local_round(st: FlowUpdatingState, pl: PlanArrays, halo: HaloTables,
                 perm: PermTables, cfg: RoundConfig, Eb: int, S: int,
                 offsets: tuple, halo_mode: str):
    """One round on one shard's block (runs inside shard_map).  Returns
    ``(state, processed, send_mask)`` — the masks feed the telemetry
    sampler; plain runs drop them (dead-code eliminated)."""
    me = jax.lax.axis_index(NODE_AXIS)
    D = cfg.delay_depth
    ltopo = _local_topo(pl)
    st, processed = deliver_phase(st, ltopo, cfg)
    st, msg_est, send_mask = fire_core(st, ltopo, cfg, processed)

    t = st.t
    slot = (t + pl.delay) % D

    # intra-shard delivery: plain local scatter, like the one-device kernel
    local_ok = send_mask & (pl.tshard == me)
    tgt = jnp.where(local_ok, pl.tlocal, Eb)
    buf_flow = st.buf_flow.at[slot, tgt].set(st.flow, mode="drop")
    buf_est = st.buf_est.at[slot, tgt].set(msg_est, mode="drop")
    buf_valid = st.buf_valid.at[slot, tgt].set(True, mode="drop")

    if halo_mode == "ppermute":
        # point-to-point halo: one ppermute per plan-time shard offset —
        # per-round traffic is each shard's own (padded, per-pair) cut-edge
        # payloads, O(cut edges), vs the all_gather broadcast's O(S * cut).
        # Routing tables are plan-time constants sharded with their rows.
        # Vector payloads ride as extra feature lanes of the same block.
        dt = st.flow.dtype
        nf = st.flow.shape[1] if st.flow.ndim > 1 else 1
        for di in range(len(offsets)):
            sidx = perm.send_idx[di]
            in_r = sidx < Eb
            slc = jnp.minimum(sidx, Eb - 1)
            v = (send_mask[slc] & in_r).astype(dt)
            payload = jnp.concatenate(
                [_lanes(st.flow[slc]), _lanes(msg_est[slc]), v[None]])
            pairs = [(s, (s + offsets[di]) % S) for s in range(S)]
            got = jax.lax.ppermute(payload, NODE_AXIS, pairs)
            rv = got[2 * nf] > 0.5
            rt = perm.recv_tlocal[di]
            slot_r = (t + perm.recv_delay[di]) % D
            tgt2 = jnp.where(rv & (rt < Eb), rt, Eb)
            buf_flow = buf_flow.at[slot_r, tgt2].set(
                _unlanes(got[:nf], st.flow), mode="drop")
            buf_est = buf_est.at[slot_r, tgt2].set(
                _unlanes(got[nf:2 * nf], st.flow), mode="drop")
            buf_valid = buf_valid.at[slot_r, tgt2].set(True, mode="drop")
    else:
        # broadcast halo: all_gather every shard's cut-edge payloads;
        # simple, one collective — and measured competitive at small S
        # (see collective_bytes_per_round for the traffic comparison)
        hidx = jnp.minimum(pl.halo_idx, Eb - 1)
        in_range = pl.halo_idx < Eb
        h_valid = send_mask[hidx] & in_range
        h_flow = st.flow[hidx]
        h_est = msg_est[hidx]

        g = lambda x: jax.lax.all_gather(x, NODE_AXIS).reshape(
            (-1,) + x.shape[1:])
        a_valid = g(h_valid)
        a_flow = g(h_flow)
        a_est = g(h_est)
        a_slot = (t + halo.delay) % D

        mine = a_valid & (halo.tshard == me)
        tgt2 = jnp.where(mine, halo.tlocal, Eb)
        buf_flow = buf_flow.at[a_slot, tgt2].set(a_flow, mode="drop")
        buf_est = buf_est.at[a_slot, tgt2].set(a_est, mode="drop")
        buf_valid = buf_valid.at[a_slot, tgt2].set(True, mode="drop")

    st = st.replace(
        t=t + 1, buf_flow=buf_flow, buf_est=buf_est, buf_valid=buf_valid
    )
    return st, processed, send_mask


def _local_round_fastpair(st: FlowUpdatingState, pl: PlanArrays,
                          halo: HaloTables, perm: PermTables,
                          cfg: RoundConfig,  # noqa: ARG001  # cfg: signature parity with _local_round (dispatch table)
                          Eb: int, S: int, offsets: tuple,
                          halo_mode: str, num_colors: int):
    """One fast-synchronous-pairwise round on one shard's block.

    Mirrors the single-device matching-gossip branch
    (``models/rounds.py:304-345``): round ``t`` fires color class
    ``t % C``; matched endpoints average *directly* (no messages, no ring
    buffer).  ``x_u`` and the sender-side validity bit of every CUT edge
    ride the existing halo machinery — the only cross-device traffic — so
    each edge sees its remote endpoint's current estimate; intra-shard
    partners are read through the local reverse slot.  Both shards of a
    cut pair compute the identical average from the identical (x_u, x_v),
    so the flow deltas are exactly antisymmetric, as on one device.
    """
    me = jax.lax.axis_index(NODE_AXIS)
    dt = st.flow.dtype
    t = st.t
    Nb = st.value.shape[0]
    half = jnp.asarray(0.5, dt)

    est_n = st.value - jax.ops.segment_sum(
        st.flow, pl.src_local, num_segments=Nb)
    F = st.flow.shape[1:]                           # () | (D,) features
    x_u = est_n[pl.src_local]                       # (Eb,) + F
    valid_u = st.alive[pl.src_local] & st.edge_ok   # sender-side half of
    #                                                 the matched predicate

    # partner state: local reverse slot, then overwrite cut slots from halo
    is_local = (pl.tshard == me) & (pl.tlocal < Eb)
    lr = jnp.minimum(pl.tlocal, Eb - 1)
    x_v = jnp.where(_ex(is_local, x_u), x_u[lr], jnp.asarray(0, dt))
    valid_v = is_local & valid_u[lr]
    nf = x_u.shape[1] if x_u.ndim > 1 else 1

    if halo_mode == "ppermute":
        for di in range(len(offsets)):
            sidx = perm.send_idx[di]
            in_r = sidx < Eb
            slc = jnp.minimum(sidx, Eb - 1)
            payload = jnp.concatenate([
                _lanes(x_u[slc]), (valid_u[slc] & in_r).astype(dt)[None]])
            pairs = [(s, (s + offsets[di]) % S) for s in range(S)]
            got = jax.lax.ppermute(payload, NODE_AXIS, pairs)
            rt = perm.recv_tlocal[di]
            tgt = jnp.where(got[nf] > 0.5, jnp.minimum(rt, Eb), Eb)
            arrived = jnp.zeros((Eb + 1,), bool).at[tgt].set(
                True, mode="drop")[:Eb]
            xin = jnp.zeros((Eb + 1,) + F, dt).at[tgt].set(
                _unlanes(got[:nf], x_u), mode="drop")[:Eb]
            x_v = jnp.where(_ex(arrived, x_v), xin, x_v)
            valid_v = valid_v | arrived
    else:
        hidx = jnp.minimum(pl.halo_idx, Eb - 1)
        in_range = pl.halo_idx < Eb
        g = lambda x: jax.lax.all_gather(x, NODE_AXIS).reshape(
            (-1,) + x.shape[1:])
        a_x = g(x_u[hidx])
        a_ok = g(valid_u[hidx] & in_range)
        mine = a_ok & (halo.tshard == me)
        tgt = jnp.where(mine, halo.tlocal, Eb)
        arrived = jnp.zeros((Eb + 1,), bool).at[tgt].set(
            True, mode="drop")[:Eb]
        xin = jnp.zeros((Eb + 1,) + F, dt).at[tgt].set(
            a_x, mode="drop")[:Eb]
        x_v = jnp.where(_ex(arrived, x_v), xin, x_v)
        valid_v = valid_v | arrived

    matched = ((pl.edge_color == t % num_colors)
               & valid_u & valid_v)
    m_ex = _ex(matched, x_u)
    avg_e = (x_u + x_v) * half
    flow = jnp.where(m_ex, st.flow + (x_u - x_v) * half, st.flow)
    est_e = jnp.where(m_ex, avg_e, st.est)
    stamp = jnp.where(matched, t, st.stamp)
    fire_any = jax.ops.segment_max(
        matched.astype(jnp.int32), pl.src_local, num_segments=Nb) > 0
    node_avg = jax.ops.segment_sum(
        jnp.where(m_ex, avg_e, jnp.asarray(0, dt)), pl.src_local,
        num_segments=Nb)
    last_avg = jnp.where(_ex(fire_any, node_avg), node_avg, st.last_avg)
    st = st.replace(
        t=t + 1, flow=flow, est=est_e, stamp=stamp, last_avg=last_avg,
        fired=st.fired + fire_any.astype(jnp.int32),
    )
    # direct exchange: no messages drained or put on the wire — the zero
    # masks keep the telemetry counters consistent with the single-device
    # fast-pairwise branch (send_mask there is all-False too)
    none = jnp.zeros((Eb,), bool)
    return st, none, none


def _round_dispatch(s, pl, halo_t, pm, ov, cfg, Eb, S, offsets,
                    halo_mode, num_colors):
    """One shard-local round for any halo mode: the serialized oracles
    ('ppermute'/'allgather') run the straight-line bodies above; the
    overlap modes run the interior/frontier-split schedule
    (:mod:`flow_updating_tpu.parallel.overlap`)."""
    from flow_updating_tpu.parallel import overlap as _ovl

    if halo_mode in _ovl.OVERLAP_MODES:
        if cfg.needs_coloring:
            return _ovl.local_round_overlap_fastpair(
                s, pl, halo_t, pm, ov, cfg, Eb, S, offsets, halo_mode,
                num_colors)
        return _ovl.local_round_overlap(
            s, pl, halo_t, pm, ov, cfg, Eb, S, offsets, halo_mode)
    if cfg.needs_coloring:
        return _local_round_fastpair(
            s, pl, halo_t, pm, cfg, Eb, S, offsets, halo_mode, num_colors)
    return _local_round(s, pl, halo_t, pm, cfg, Eb, S, offsets, halo_mode)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "num_rounds", "Eb", "offsets",
                     "halo_mode", "num_colors"),
)
def _run_sharded(state, arrays, halo, perm, ov, cfg, mesh, num_rounds, Eb,
                 offsets, halo_mode, num_colors=0):
    state_specs = _state_specs(state, mesh)
    plan_specs = jax.tree.map(_spec, arrays)
    halo_specs = jax.tree.map(lambda _: P(), halo)
    perm_specs = jax.tree.map(_spec, perm)
    ov_specs = jax.tree.map(_spec, ov)
    S = int(mesh.shape[NODE_AXIS])  # node-axis size (2-D mesh aware)

    def body(st_s, pl_s, halo_t, pm_s, ov_s):
        st = jax.tree.map(lambda x: x[0], st_s)
        pl = jax.tree.map(lambda x: x[0], pl_s)
        pm = jax.tree.map(lambda x: x[0], pm_s)
        ovl = jax.tree.map(lambda x: x[0], ov_s)

        def step(s, _):
            s2, _, _ = _round_dispatch(
                s, pl, halo_t, pm, ovl, cfg, Eb, S, offsets, halo_mode,
                num_colors)
            return s2, None

        st, _ = jax.lax.scan(step, st, None, length=num_rounds)
        return jax.tree.map(lambda x: x[None], st)

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, plan_specs, halo_specs, perm_specs,
                  ov_specs),
        out_specs=state_specs,
        check_vma=False,
    )
    return fn(state, arrays, halo, perm, ov)


def run_rounds_sharded(
    state: FlowUpdatingState,
    plan: ShardPlan,
    cfg: RoundConfig,
    mesh: jax.sharding.Mesh,
    num_rounds: int,
    arrays: tuple[PlanArrays, HaloTables, PermTables] | None = None,
    halo: str = "ppermute",
) -> FlowUpdatingState:
    """Run ``num_rounds`` sharded rounds as one compiled shard_map'd scan.

    ``halo`` selects the cut-edge exchange: ``'ppermute'`` (point-to-point,
    O(cut) traffic), ``'allgather'`` (broadcast; one collective,
    competitive at small S), ``'overlap'`` (the interior/frontier-split
    schedule — same wire as ppermute, started before the interior
    compute so async collectives hide it; bit-exact vs ppermute), or
    ``'overlap_pallas'`` (the split schedule with the Pallas
    ``make_async_remote_copy`` kernel carrying the wire — the TPU-native
    fused form, interpret-mode-tested off TPU).
    """
    fn, args, _ = round_program(state, plan, cfg, mesh, num_rounds,
                                arrays=arrays, halo=halo)
    return fn(*args)


def _program_inputs(plan: ShardPlan, cfg: RoundConfig, mesh, arrays,
                    halo: str, *, _internal: bool = False):
    """Shared preamble of the program builders: validate the config/halo
    combination, resolve the overlap schedule (plan-time fat-frontier
    rewrite), and materialize the device array tuple.  Returns
    ``(plan_arrays, halo_tables, perm, ov, resolved_halo)``."""
    if cfg.needs_coloring and plan.num_colors == 0:
        raise ValueError(
            "fast synchronous pairwise needs the edge coloring in the "
            "plan: build it with plan_sharding(..., coloring=True)"
        )
    _check_halo(halo, _internal=_internal)
    if cfg.contention:
        raise NotImplementedError(
            "contention is single-device (per-round link flow counts are a "
            "global reduction; fidelity runs are platform-scale)"
        )
    from flow_updating_tpu.parallel import overlap as _ovl

    halo = _ovl.resolve_mode(plan, halo)
    if arrays is None:
        arrays = plan_device_arrays(plan, mesh, halo=halo)
    plan_arrays, halo_tables, perm, ov = arrays
    if ov is None and halo in _ovl.OVERLAP_MODES:
        ov = _overlap_device_tables(plan, mesh)
    return plan_arrays, halo_tables, perm, ov, halo


def round_program(state, plan: ShardPlan, cfg: RoundConfig,
                  mesh: jax.sharding.Mesh, num_rounds: int,
                  arrays=None, halo: str = "ppermute",
                  _internal: bool = False):
    """``(jitted_fn, full_args, n_dynamic)`` for the plain sharded round
    scan — :func:`run_rounds_sharded` calls through this, and the AOT
    cost-attribution layer (:mod:`flow_updating_tpu.obs.profile`) lowers
    the same split, so the profiled executable IS the plain program.

    ``halo='interior'`` is the overlap schedule with the exchange
    elided — a TIMING PROBE for ``obs.profile.overlap_report``, not a
    correct protocol mode; it (and the plan-time ``'overlap_full'``
    resolution) is accepted only with ``_internal=True``."""
    plan_arrays, halo_tables, perm, ov, halo = _program_inputs(
        plan, cfg, mesh, arrays, halo, _internal=_internal)
    return (_run_sharded,
            (state, plan_arrays, halo_tables, perm, ov, cfg, mesh,
             num_rounds, plan.Eb, plan.perm_offsets, halo,
             plan.num_colors), 5)


def _halo_telemetry_sample(st: FlowUpdatingState, pl: PlanArrays, spec,
                           mean, processed, send_mask, Nb: int) -> dict:
    """One round's metric row on one shard, ``psum``-reduced over the mesh
    axis so every shard holds the GLOBAL value — the series then matches
    the single-device edge kernel's bit-for-bit up to reduction order
    (asserted in tests/test_telemetry.py).  Padding rows are dead dummies
    (alive=False, value 0), so the alive mask excludes them exactly like
    mesh padding on the GSPMD path."""
    from flow_updating_tpu.models.rounds import _fired_acc

    psum = lambda x: jax.lax.psum(x, NODE_AXIS)
    out = {"t": st.t}
    alive = st.alive
    need_est = any(spec.has(m) for m in
                   ("rmse", "max_abs_err", "mass", "mass_residual"))
    if need_est:
        est = st.value - jax.ops.segment_sum(
            st.flow, pl.src_local, num_segments=Nb)
        a_ex = _ex(alive, est)
        if spec.has("rmse") or spec.has("max_abs_err"):
            err = jnp.where(a_ex, est - mean, 0)
            if spec.has("rmse"):
                feat = int(est.size // est.shape[0]) if est.ndim > 1 else 1
                cnt = (jnp.maximum(
                    psum(jnp.sum(alive.astype(jnp.int32))), 1)
                    * feat).astype(est.dtype)
                out["rmse"] = jnp.sqrt(psum(jnp.sum(err * err)) / cnt)
            if spec.has("max_abs_err"):
                out["max_abs_err"] = jax.lax.pmax(
                    jnp.max(jnp.abs(err)), NODE_AXIS)
        if spec.has("mass") or spec.has("mass_residual"):
            mass = psum(jnp.sum(jnp.where(a_ex, est, 0), axis=0))
            if spec.has("mass"):
                out["mass"] = mass
            if spec.has("mass_residual"):
                out["mass_residual"] = mass - psum(jnp.sum(
                    jnp.where(_ex(alive, st.value), st.value, 0), axis=0))
    if spec.has("sent"):
        out["sent"] = psum(jnp.sum(send_mask.astype(jnp.int32)))
    if spec.has("delivered"):
        out["delivered"] = psum(jnp.sum(processed.astype(jnp.int32)))
    if spec.has("fired_total"):
        out["fired_total"] = psum(jnp.sum(st.fired, dtype=_fired_acc()))
    if spec.has("active"):
        out["active"] = psum(jnp.sum(alive.astype(jnp.int32)))
    return out


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "num_rounds", "Eb", "Nb", "offsets",
                     "halo_mode", "num_colors", "spec"),
)
def _run_sharded_telemetry(state, arrays, halo, perm, ov, mean, cfg, mesh,
                           num_rounds, Eb, Nb, offsets, halo_mode,
                           num_colors, spec):
    if _feature_shards(mesh) > 1:
        raise NotImplementedError(
            "telemetry series on the 2-D (nodes, feature) mesh are not "
            "wired (the metric reductions would need a feature-axis "
            "psum); run telemetry on a 1-D node mesh or use the "
            "chunked-schedule telemetry (models/rounds.py)")
    state_specs = jax.tree.map(_spec, state)
    plan_specs = jax.tree.map(_spec, arrays)
    halo_specs = jax.tree.map(lambda _: P(), halo)
    perm_specs = jax.tree.map(_spec, perm)
    ov_specs = jax.tree.map(_spec, ov)
    S = int(mesh.shape[NODE_AXIS])  # node-axis size (2-D mesh aware)

    def body(st_s, pl_s, halo_t, pm_s, ov_s, mean_r):
        st = jax.tree.map(lambda x: x[0], st_s)
        pl = jax.tree.map(lambda x: x[0], pl_s)
        pm = jax.tree.map(lambda x: x[0], pm_s)
        ovl = jax.tree.map(lambda x: x[0], ov_s)

        def step(s, _):
            s2, pr, sm = _round_dispatch(
                s, pl, halo_t, pm, ovl, cfg, Eb, S, offsets, halo_mode,
                num_colors)
            m = _halo_telemetry_sample(s2, pl, spec, mean_r, pr, sm, Nb)
            return s2, m

        st, series = jax.lax.scan(step, st, None, length=num_rounds)
        # series values are post-psum identical on every shard; stack a
        # unit shard axis so the out_spec can shard it like everything
        # else (the host reads block 0)
        return (jax.tree.map(lambda x: x[None], st),
                jax.tree.map(lambda x: x[None], series))

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, plan_specs, halo_specs, perm_specs,
                  ov_specs, P()),
        out_specs=(state_specs, P(NODE_AXIS)),
        check_vma=False,
    )
    return fn(state, arrays, halo, perm, ov, mean)


def run_rounds_sharded_telemetry(
    state: FlowUpdatingState,
    plan: ShardPlan,
    cfg: RoundConfig,
    mesh: jax.sharding.Mesh,
    num_rounds: int,
    spec,
    true_mean,
    arrays: tuple[PlanArrays, HaloTables, PermTables] | None = None,
    halo: str = "ppermute",
):
    """Telemetry twin of :func:`run_rounds_sharded`: one compiled
    shard_map'd scan whose ys are the psum-reduced global metric series.
    Returns ``(state, {metric: (R, ...) device array})``."""
    if not spec.enabled:
        raise ValueError(
            "telemetry spec is disabled; run run_rounds_sharded() instead")
    plan_arrays, halo_tables, perm, ov, halo = _program_inputs(
        plan, cfg, mesh, arrays, halo)
    mean = jnp.asarray(true_mean, state.value.dtype)
    state, series = _run_sharded_telemetry(
        state, plan_arrays, halo_tables, perm, ov, mean, cfg, mesh,
        num_rounds, plan.Eb, plan.Nb, plan.perm_offsets, halo,
        plan.num_colors, spec,
    )
    return state, {k: v[0] for k, v in series.items()}


def _halo_field_sample(st: FlowUpdatingState, pl: PlanArrays, spec, mean,
                       Nb: int):
    """One recorded per-node/per-edge field row on one shard, in the
    LOCAL block layout (the host gathers back to original order with
    :func:`gather_node_field_series` / :func:`gather_edge_field_series`).
    Only ``t``/``active`` are collective (one scalar psum); the fields
    themselves stay shard-local.  Masking matches
    :func:`_halo_telemetry_sample` (padding rows are dead dummies)."""
    from flow_updating_tpu.models.rounds import _pool_sum

    row = {"t": st.t,
           "active": jax.lax.psum(
               jnp.sum(st.alive.astype(jnp.int32)), NODE_AXIS)}
    err = None
    need_est = any(spec.has(f) for f in
                   ("node_err", "node_mass", "node_mass_residual",
                    "node_conv_round"))
    if need_est:
        est = st.value - jax.ops.segment_sum(
            st.flow, pl.src_local, num_segments=Nb)
        a_ex = _ex(st.alive, est)
        err = jnp.where(a_ex, est - mean, 0)
        if spec.has("node_err"):
            row["node_err"] = err
        if spec.has("node_mass"):
            row["node_mass"] = jnp.where(a_ex, est, 0)
        if spec.has("node_mass_residual"):
            row["node_mass_residual"] = jnp.where(a_ex, est - st.value, 0)
    if spec.has("node_fired"):
        row["node_fired"] = st.fired
    if spec.has("edge_flow"):
        row["edge_flow"] = _pool_sum(st.flow)
    if spec.has("edge_est"):
        row["edge_est"] = _pool_sum(st.est)
    if spec.has("edge_stale"):
        row["edge_stale"] = st.t - st.stamp
    return row, err


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "mesh", "num_rounds", "Eb", "Nb", "offsets",
                     "halo_mode", "num_colors", "spec"),
)
def _run_sharded_fields(state, arrays, halo, perm, ov, mean, cfg, mesh,
                        num_rounds, Eb, Nb, offsets, halo_mode,
                        num_colors, spec):
    from flow_updating_tpu.models.rounds import _pool_abs

    if _feature_shards(mesh) > 1:
        raise NotImplementedError(
            "field series on the 2-D (nodes, feature) mesh are not "
            "wired (per-entity reductions would need a feature-axis "
            "psum); run fields on a 1-D node mesh")
    state_specs = jax.tree.map(_spec, state)
    plan_specs = jax.tree.map(_spec, arrays)
    halo_specs = jax.tree.map(lambda _: P(), halo)
    perm_specs = jax.tree.map(_spec, perm)
    ov_specs = jax.tree.map(_spec, ov)
    S = int(mesh.shape[NODE_AXIS])  # node-axis size (2-D mesh aware)
    stride = spec.stride
    track_conv = spec.has("node_conv_round")

    def body(st_s, pl_s, halo_t, pm_s, ov_s, mean_r):
        st = jax.tree.map(lambda x: x[0], st_s)
        pl = jax.tree.map(lambda x: x[0], pl_s)
        pm = jax.tree.map(lambda x: x[0], pm_s)
        ovl = jax.tree.map(lambda x: x[0], ov_s)

        def one_round(_, s):
            return _round_dispatch(
                s, pl, halo_t, pm, ovl, cfg, Eb, S, offsets, halo_mode,
                num_colors)[0]

        def chunk(carry, _):
            s, conv = carry
            s = jax.lax.fori_loop(0, stride, one_round, s)
            row, err = _halo_field_sample(s, pl, spec, mean_r, Nb)
            if track_conv:
                within = (_pool_abs(err) <= spec.tol) & s.alive
                conv = jnp.where((conv < 0) & within, s.t, conv)
            return (s, conv), row

        conv0 = jnp.full((Nb,), -1, jnp.int32)
        (st, conv), series = jax.lax.scan(
            chunk, (st, conv0), None, length=num_rounds // stride)
        # stack a unit shard axis on everything so the out_specs can
        # concatenate the per-shard blocks (host reassembles from them)
        return (jax.tree.map(lambda x: x[None], st), conv[None],
                jax.tree.map(lambda x: x[None], series))

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, plan_specs, halo_specs, perm_specs,
                  ov_specs, P()),
        out_specs=(state_specs, P(NODE_AXIS), P(NODE_AXIS)),
        check_vma=False,
    )
    return fn(state, arrays, halo, perm, ov, mean)


def run_rounds_sharded_fields(
    state: FlowUpdatingState,
    plan: ShardPlan,
    cfg: RoundConfig,
    mesh: jax.sharding.Mesh,
    num_rounds: int,
    spec,
    true_mean,
    arrays: tuple[PlanArrays, HaloTables, PermTables] | None = None,
    halo: str = "ppermute",
):
    """Fields twin of :func:`run_rounds_sharded_telemetry`: one compiled
    shard_map'd scan whose ys are the shard-local field blocks.  Returns
    ``(state, conv_round, series)`` with ``conv_round`` ``(S, Nb)`` and
    each series leaf ``(S, R, Nb/Eb, ...)`` — still blocked;
    ``Engine.run_fields`` gathers them to original order."""
    if not spec.enabled:
        raise ValueError(
            "field spec is disabled; run run_rounds_sharded() instead")
    if num_rounds % spec.stride:
        raise ValueError(
            f"num_rounds={num_rounds} must be a multiple of the field "
            f"stride {spec.stride}")
    plan_arrays, halo_tables, perm, ov, halo = _program_inputs(
        plan, cfg, mesh, arrays, halo)
    mean = jnp.asarray(true_mean, state.value.dtype)
    return _run_sharded_fields(
        state, plan_arrays, halo_tables, perm, ov, mean, cfg, mesh,
        num_rounds, plan.Eb, plan.Nb, plan.perm_offsets, halo,
        plan.num_colors, spec,
    )


def gather_node_field_series(x, plan: ShardPlan) -> np.ndarray:
    """A stacked per-node field series ``(S, R, Nb, ...)`` -> ``(R, N,
    ...)`` in the caller's original node order (drops the per-shard dummy
    row and the tail padding, undoes any partition reorder)."""
    x = np.asarray(x)
    R = x.shape[1]
    rest = x.shape[3:]
    x = x[:, :, : plan.cap]
    x = np.moveaxis(x, 0, 1).reshape((R, plan.num_shards * plan.cap)
                                     + rest)[:, : plan.topo.num_nodes]
    if plan.order is None:
        return x.copy()
    out = np.empty_like(x)
    out[:, plan.order] = x
    return out


def gather_edge_field_series(x, plan: ShardPlan, orig_topo) -> np.ndarray:
    """A stacked per-edge field series ``(S, R, Eb)`` -> ``(R, E)`` in
    ``orig_topo``'s edge order (via the plan's edge ownership map)."""
    if plan.edge_shard is None:
        raise ValueError("plan lacks the edge ownership map")
    e_of_orig = _edge_map_to_original(plan, orig_topo)
    es = plan.edge_shard[e_of_orig]
    ep = plan.edge_slot[e_of_orig]
    return np.asarray(x)[es, :, ep].T


def gather_estimates(state: FlowUpdatingState, plan: ShardPlan) -> np.ndarray:
    """Per-node estimates in the caller's *original* node order
    (host-side; undoes both the block layout and any partition reorder)."""
    S, Nb, Eb, N = plan.num_shards, plan.Nb, plan.Eb, plan.topo.num_nodes
    flow = np.asarray(state.flow)
    value = np.asarray(state.value)
    src = np.asarray(plan.arrays.src_local)
    F = flow.shape[2:]                 # trailing feature axes (vector)
    sums = np.zeros((S, Nb) + F, flow.dtype)
    for s in range(S):
        np.add.at(sums[s], src[s], flow[s])
    est = value - sums
    return _unpermute(est[:, : plan.cap].reshape((-1,) + F)[:N], plan)


def gather_node_array(x, plan: ShardPlan) -> np.ndarray:
    """Unpad a (S, Nb, ...)-stacked per-node array back to the original
    global node order (trailing feature axes pass through)."""
    N = plan.topo.num_nodes
    x = np.asarray(x)
    return _unpermute(
        x[:, : plan.cap].reshape((-1,) + x.shape[2:])[:N], plan)


def _unpermute(x: np.ndarray, plan: ShardPlan) -> np.ndarray:
    if plan.order is None:
        return x.copy()
    out = np.empty_like(x)
    out[plan.order] = x
    return out


def _edge_map_to_original(plan: ShardPlan, orig_topo) -> np.ndarray:
    """(E,) map: ORIGINAL edge index -> index into the plan's (possibly
    BFS-reordered) global edge order.  Identity when no reorder."""
    if plan.order is None:
        return np.arange(plan.topo.num_edges, dtype=np.int64)
    # reordered edge r = (src', dst') is original pair
    # (order[src'], order[dst']); locate it in the original sorted list
    rt, ot = plan.topo, orig_topo
    o_src = plan.order[rt.src.astype(np.int64)]
    o_dst = plan.order[rt.dst.astype(np.int64)]
    keys = ot.src.astype(np.int64) * ot.num_nodes + ot.dst
    want = o_src * ot.num_nodes + o_dst
    pos = np.searchsorted(keys, want)
    # clip before the equality probe: an out-of-range key must surface as
    # the diagnostic below, not an IndexError
    probe = np.minimum(pos, len(keys) - 1)
    if not np.array_equal(keys[probe], want):
        raise ValueError("plan topology is not a renumbering of the "
                         "original (edge sets differ)")
    # pos[r] = original index of reordered edge r; invert
    inv = np.empty_like(pos)
    inv[pos] = np.arange(len(pos), dtype=np.int64)
    return inv


def gather_full_state(state: FlowUpdatingState, plan: ShardPlan,
                      orig_topo) -> FlowUpdatingState:
    """The blocked (S, .) halo state as a CANONICAL single-device
    :class:`FlowUpdatingState` in ``orig_topo``'s node/edge order — the
    layout ``init_state`` produces, so the result checkpoints and
    restores through the standard path (and can resume on any execution
    mode).  The PRNG key collapses to shard 0's (drop-rate streams are
    not bit-continued across layouts)."""
    import jax

    if plan.edge_shard is None:
        raise ValueError("plan lacks the edge ownership map")
    e_of_orig = _edge_map_to_original(plan, orig_topo)
    es = plan.edge_shard[e_of_orig]
    ep = plan.edge_slot[e_of_orig]
    host = jax.device_get(state)

    def edge(x):          # (S, Eb) -> (E,) original order
        return np.asarray(x)[es, ep]

    def edge_planes(x):   # (S, K, Eb) -> (K, E)
        return np.asarray(x)[es, :, ep].T

    def node(x):
        return gather_node_array(x, plan)

    return FlowUpdatingState(
        t=np.asarray(host.t).ravel()[0],
        value=node(host.value),
        flow=edge(host.flow),
        est=edge(host.est),
        recv=edge(host.recv),
        ticks=node(host.ticks),
        stamp=edge(host.stamp),
        last_avg=node(host.last_avg),
        fired=node(host.fired),
        alive=node(host.alive),
        edge_ok=edge(host.edge_ok),
        pending_flow=edge_planes(host.pending_flow),
        pending_est=edge_planes(host.pending_est),
        pending_valid=edge_planes(host.pending_valid),
        pending_stamp=edge_planes(host.pending_stamp),
        buf_flow=edge_planes(host.buf_flow),
        buf_est=edge_planes(host.buf_est),
        buf_valid=edge_planes(host.buf_valid),
        key=np.asarray(host.key)[0],
    )


def scatter_full_state(state: FlowUpdatingState, plan: ShardPlan,
                       orig_topo, cfg: RoundConfig,
                       mesh: jax.sharding.Mesh) -> FlowUpdatingState:
    """Inverse of :func:`gather_full_state`: distribute a canonical
    single-device state into the plan's blocked layout (device-placed).
    Padding slots take the fresh-init values (dead dummies, zero
    ledgers)."""
    import jax

    template = jax.device_get(init_plan_state(plan, cfg, mesh))
    e_of_orig = _edge_map_to_original(plan, orig_topo)
    es = plan.edge_shard[e_of_orig]
    ep = plan.edge_slot[e_of_orig]
    S, cap = plan.num_shards, plan.cap
    N = orig_topo.num_nodes
    # node arrays: original order -> partition order -> (S, cap) blocks
    norder = (plan.order if plan.order is not None
              else np.arange(N, dtype=np.int64))

    def node(canon, tmpl):
        out = np.array(tmpl)
        flat = np.asarray(canon)[norder]           # partition order
        pad = np.zeros(S * cap - N, flat.dtype)
        out[:, :cap] = np.concatenate([flat, pad]).reshape(S, cap)
        return out

    def edge(canon, tmpl):
        out = np.array(tmpl)
        out[es, ep] = np.asarray(canon)
        return out

    def edge_planes(canon, tmpl):
        out = np.array(tmpl)
        out[es, :, ep] = np.asarray(canon).T
        return out

    new = FlowUpdatingState(
        t=np.full((S,), int(np.asarray(state.t)), np.int32),
        value=node(state.value, template.value),
        flow=edge(state.flow, template.flow),
        est=edge(state.est, template.est),
        recv=edge(state.recv, template.recv),
        ticks=node(state.ticks, template.ticks),
        stamp=edge(state.stamp, template.stamp),
        last_avg=node(state.last_avg, template.last_avg),
        fired=node(state.fired, template.fired),
        alive=node(state.alive, template.alive),
        edge_ok=edge(state.edge_ok, template.edge_ok),
        pending_flow=edge_planes(state.pending_flow, template.pending_flow),
        pending_est=edge_planes(state.pending_est, template.pending_est),
        pending_valid=edge_planes(state.pending_valid,
                                  template.pending_valid),
        pending_stamp=edge_planes(state.pending_stamp,
                                  template.pending_stamp),
        buf_flow=edge_planes(state.buf_flow, template.buf_flow),
        buf_est=edge_planes(state.buf_est, template.buf_est),
        buf_valid=edge_planes(state.buf_valid, template.buf_valid),
        # per-shard independent streams, like init_plan_state: tiling the
        # single key would correlate every shard's stochastic decisions
        key=np.asarray(jax.vmap(
            lambda i: jax.random.fold_in(
                jnp.asarray(state.key, jnp.uint32), i)
        )(jnp.arange(S))),
    )
    return jax.device_put(new, _sharding_tree(new, mesh))
