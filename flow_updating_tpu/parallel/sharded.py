"""Explicitly scheduled multi-chip execution: ``shard_map`` + halo exchange.

The GSPMD path (:mod:`flow_updating_tpu.parallel.auto`) hands XLA globally
annotated arrays and lets the SPMD partitioner place collectives.  This
module is the hand-scheduled alternative — the TPU-native analogue of the
reference's point-to-point mailbox delivery across hosts (SimGrid's
rendezvous matching, SURVEY.md N4), done the way a multi-pod gossip system
would actually run:

* nodes are partitioned into contiguous blocks, one block per device; every
  directed edge lives with its *source* node's shard, so segment reductions
  and firing decisions are purely local;
* the only cross-device traffic is message delivery on *cut* edges (edges
  whose reverse lives on another shard).  Those are compiled into a fixed
  per-shard halo send list at plan time; each round the payloads (flow,
  estimate, valid) are exchanged with ``lax.all_gather`` over the mesh axis
  (ICI) and scattered into the receiver's ring-buffer slot.  The routing
  tables (target shard/slot/delay per halo entry) are plan-time constants,
  replicated once — never re-communicated;
* intra-shard edges deliver with a local scatter, exactly like the
  single-device kernel.

The per-round collective volume is ``S * H * (2 floats + 1 bool)`` (H = max
cut edges per shard) — independent of the number of intra-shard edges, so a
community-structured partition keeps ICI traffic tiny.

The round math itself is shared with the single-device kernel
(:func:`flow_updating_tpu.models.rounds.deliver_phase` /
:func:`~flow_updating_tpu.models.rounds.fire_core` run unchanged on local
shard views); only message *delivery* differs.  The fast synchronous
pairwise mode is the one exception (its direct two-sided exchange reads the
remote endpoint's estimate, see ``rounds.py``) — use the GSPMD path for it.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState
from flow_updating_tpu.models.rounds import deliver_phase, fire_core
from flow_updating_tpu.parallel.mesh import NODE_AXIS
from flow_updating_tpu.topology.graph import Topology, TopoArrays

P = jax.sharding.PartitionSpec
shard_map = jax.shard_map


@flax.struct.dataclass
class PlanArrays:
    """Per-shard device arrays, stacked on a leading shard axis (S, ...)."""

    src_local: jnp.ndarray   # (S, Eb) i32 — local source node of each edge slot
    out_deg: jnp.ndarray     # (S, Nb) i32 — real out-degree per local node
    row_start: jnp.ndarray   # (S, Nb+1) i32 — local CSR offsets
    edge_rank: jnp.ndarray   # (S, Eb) i32 — rank within local src row
    delay: jnp.ndarray       # (S, Eb) i32 — delivery delay in rounds
    tshard: jnp.ndarray      # (S, Eb) i32 — shard owning rev(edge)
    tlocal: jnp.ndarray      # (S, Eb) i32 — rev(edge)'s slot there (Eb = none)
    halo_idx: jnp.ndarray    # (S, H) i32 — slots of cut edges (Eb = padding)


@flax.struct.dataclass
class HaloTables:
    """Replicated plan-time routing tables for halo entries, in all_gather
    (shard-major) order.  Constant across rounds — kept out of the per-round
    collective entirely."""

    tshard: jnp.ndarray  # (S*H,) i32 — receiving shard (-1 = padding)
    tlocal: jnp.ndarray  # (S*H,) i32 — slot there (Eb = padding)
    delay: jnp.ndarray   # (S*H,) i32 — sending edge's delivery delay


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Host-side sharding plan for one topology on S devices."""

    topo: Topology
    num_shards: int
    cap: int            # real nodes per shard (last shard may be short)
    Nb: int             # local node count incl. the per-shard dummy (cap + 1)
    Eb: int             # padded edge slots per shard
    H: int              # padded halo (cut-edge) slots per shard
    arrays: PlanArrays  # numpy-backed; device_put at init
    halo: HaloTables    # numpy-backed, replicated at init
    values: np.ndarray  # (S, Nb) initial node values (0 on padding)
    alive0: np.ndarray  # (S, Nb) bool initial liveness (False on padding)

    @property
    def cut_fraction(self) -> float:
        """Fraction of directed edges whose delivery crosses shards."""
        idx = np.asarray(self.arrays.halo_idx)
        return float((idx < self.Eb).sum()) / max(self.topo.num_edges, 1)


def plan_sharding(topo: Topology, num_shards: int) -> ShardPlan:
    """Partition nodes into contiguous blocks and edges with their source.

    Local node ``Nb-1`` of every shard is a dummy (dead, value 0) that owns
    the padded edge slots, so padding can never fire or send.
    """
    N, E, S = topo.num_nodes, topo.num_edges, num_shards
    cap = max(1, math.ceil(N / S))
    Nb = cap + 1
    shard_of = topo.src.astype(np.int64) // cap
    local_of = topo.src.astype(np.int64) % cap

    counts = np.bincount(shard_of, minlength=S)
    Eb = max(int(counts.max()) if E else 0, 1)
    # position of each edge within its shard (edges are (src, dst)-sorted, so
    # per-shard order stays sorted by local (src, dst))
    starts = np.zeros(S + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(E, dtype=np.int64) - starts[shard_of]

    owner_shard = shard_of            # per global edge
    owner_pos = pos
    rev_shard = owner_shard[topo.rev]
    rev_pos = owner_pos[topo.rev]

    src_local = np.full((S, Eb), Nb - 1, np.int32)
    delay = np.ones((S, Eb), np.int32)
    tshard = np.tile(
        np.arange(S, dtype=np.int32).reshape(S, 1), (1, Eb)
    )
    tlocal = np.full((S, Eb), Eb, np.int32)

    src_local[owner_shard, owner_pos] = local_of
    delay[owner_shard, owner_pos] = topo.delay
    tshard[owner_shard, owner_pos] = rev_shard
    tlocal[owner_shard, owner_pos] = rev_pos

    # local CSR (padded slots all belong to the dummy row at the end)
    out_deg = np.zeros((S, Nb), np.int32)
    np.add.at(out_deg, (owner_shard, local_of), 1)
    row_start = np.zeros((S, Nb + 1), np.int32)
    full_deg = out_deg.copy()
    full_deg[:, Nb - 1] += Eb - counts.astype(np.int32)
    np.cumsum(full_deg, axis=1, out=row_start[:, 1:])
    slot_idx = np.tile(np.arange(Eb, dtype=np.int64), (S, 1))
    edge_rank = (slot_idx - row_start[np.arange(S)[:, None], src_local]).astype(
        np.int32
    )

    # halo send lists: cut-edge slots, padded with the Eb sentinel
    is_cut = (tshard != np.arange(S, dtype=np.int32).reshape(S, 1)) & (
        tlocal < Eb
    )
    H = max(int(is_cut.sum(axis=1).max()), 1)
    halo_idx = np.full((S, H), Eb, np.int32)
    for s in range(S):
        slots = np.where(is_cut[s])[0]
        halo_idx[s, : len(slots)] = slots

    vals_flat = np.zeros(S * cap, np.float64)
    vals_flat[:N] = topo.values
    alive_flat = np.zeros(S * cap, bool)
    alive_flat[:N] = True
    values = np.zeros((S, Nb), np.float64)
    values[:, :cap] = vals_flat.reshape(S, cap)
    alive0 = np.zeros((S, Nb), bool)
    alive0[:, :cap] = alive_flat.reshape(S, cap)

    # replicated routing tables in all_gather (shard-major) order
    hi = np.minimum(halo_idx, Eb - 1)
    h_ok = halo_idx < Eb
    sidx = np.arange(S)[:, None]
    halo = HaloTables(
        tshard=np.where(h_ok, tshard[sidx, hi], -1).astype(np.int32).ravel(),
        tlocal=np.where(h_ok, tlocal[sidx, hi], Eb).astype(np.int32).ravel(),
        delay=np.where(h_ok, delay[sidx, hi], 1).astype(np.int32).ravel(),
    )

    arrays = PlanArrays(
        src_local=src_local,
        out_deg=out_deg,
        row_start=row_start,
        edge_rank=edge_rank,
        delay=delay,
        tshard=tshard,
        tlocal=tlocal,
        halo_idx=halo_idx,
    )
    return ShardPlan(
        topo=topo, num_shards=S, cap=cap, Nb=Nb, Eb=Eb, H=H, arrays=arrays,
        halo=halo, values=values, alive0=alive0,
    )


def _spec(x) -> P:
    return P(NODE_AXIS, *([None] * (np.ndim(x) - 1)))


def _sharding_tree(tree, mesh):
    return jax.tree.map(
        lambda x: jax.sharding.NamedSharding(mesh, _spec(x)), tree
    )


def init_plan_state(
    plan: ShardPlan, cfg: RoundConfig, mesh: jax.sharding.Mesh, seed: int = 0
) -> FlowUpdatingState:
    """Fresh sharded state: every leaf carries a leading (S,) shard axis and
    is placed with its block on its device."""
    if cfg.needs_coloring:
        raise NotImplementedError(
            "fast synchronous pairwise reads the remote endpoint's estimate; "
            "use the GSPMD path (flow_updating_tpu.parallel.auto) for it"
        )
    S, Nb, Eb, D = plan.num_shards, plan.Nb, plan.Eb, cfg.delay_depth
    dt = cfg.jnp_dtype
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(seed), i))(
        jnp.arange(S)
    )
    state = FlowUpdatingState(
        t=jnp.zeros((S,), jnp.int32),
        value=jnp.asarray(plan.values, dt),
        flow=jnp.zeros((S, Eb), dt),
        est=jnp.zeros((S, Eb), dt),
        recv=jnp.zeros((S, Eb), bool),
        ticks=jnp.zeros((S, Nb), jnp.int32),
        stamp=jnp.zeros((S, Eb), jnp.int32),
        last_avg=jnp.zeros((S, Nb), dt),
        fired=jnp.zeros((S, Nb), jnp.int32),
        alive=jnp.asarray(plan.alive0),
        edge_ok=jnp.ones((S, Eb), bool),
        pending_flow=jnp.zeros((S, cfg.pending_depth, Eb), dt),
        pending_est=jnp.zeros((S, cfg.pending_depth, Eb), dt),
        pending_valid=jnp.zeros((S, cfg.pending_depth, Eb), bool),
        pending_stamp=jnp.zeros((S, cfg.pending_depth, Eb), jnp.int32),
        buf_flow=jnp.zeros((S, D, Eb), dt),
        buf_est=jnp.zeros((S, D, Eb), dt),
        buf_valid=jnp.zeros((S, D, Eb), bool),
        key=keys,
    )
    return jax.device_put(state, _sharding_tree(state, mesh))


def plan_device_arrays(
    plan: ShardPlan, mesh: jax.sharding.Mesh
) -> tuple[PlanArrays, HaloTables]:
    """Device placement: per-shard arrays blocked over the mesh, halo
    routing tables replicated."""
    arrays = jax.tree.map(jnp.asarray, plan.arrays)
    arrays = jax.device_put(arrays, _sharding_tree(arrays, mesh))
    rep = jax.sharding.NamedSharding(mesh, P())
    halo = jax.device_put(jax.tree.map(jnp.asarray, plan.halo), rep)
    return arrays, halo


def _local_round(st: FlowUpdatingState, pl: PlanArrays, halo: HaloTables,
                 cfg: RoundConfig, Eb: int):
    """One round on one shard's block (runs inside shard_map)."""
    me = jax.lax.axis_index(NODE_AXIS)
    D = cfg.delay_depth
    ltopo = TopoArrays(
        src=pl.src_local,
        dst=pl.src_local,  # placeholder: no local path reads dst
        rev=pl.tlocal,     # placeholder: delivery goes through tshard/tlocal
        out_deg=pl.out_deg,
        row_start=pl.row_start,
        edge_rank=pl.edge_rank,
        delay=pl.delay,
    )
    st, processed = deliver_phase(st, ltopo, cfg)
    st, msg_est, send_mask = fire_core(st, ltopo, cfg, processed)

    t = st.t
    slot = (t + pl.delay) % D

    # intra-shard delivery: plain local scatter, like the one-device kernel
    local_ok = send_mask & (pl.tshard == me)
    tgt = jnp.where(local_ok, pl.tlocal, Eb)
    buf_flow = st.buf_flow.at[slot, tgt].set(st.flow, mode="drop")
    buf_est = st.buf_est.at[slot, tgt].set(msg_est, mode="drop")
    buf_valid = st.buf_valid.at[slot, tgt].set(True, mode="drop")

    # halo exchange: all_gather only the *payloads* of this shard's cut
    # edges; routing (target shard/slot/delay) comes from the replicated
    # plan-time tables, and t is lockstep across shards
    hidx = jnp.minimum(pl.halo_idx, Eb - 1)
    in_range = pl.halo_idx < Eb
    h_valid = send_mask[hidx] & in_range
    h_flow = st.flow[hidx]
    h_est = msg_est[hidx]

    g = lambda x: jax.lax.all_gather(x, NODE_AXIS).reshape(-1)
    a_valid = g(h_valid)
    a_flow = g(h_flow)
    a_est = g(h_est)
    a_slot = (t + halo.delay) % D

    mine = a_valid & (halo.tshard == me)
    tgt2 = jnp.where(mine, halo.tlocal, Eb)
    buf_flow = buf_flow.at[a_slot, tgt2].set(a_flow, mode="drop")
    buf_est = buf_est.at[a_slot, tgt2].set(a_est, mode="drop")
    buf_valid = buf_valid.at[a_slot, tgt2].set(True, mode="drop")

    return st.replace(
        t=t + 1, buf_flow=buf_flow, buf_est=buf_est, buf_valid=buf_valid
    )


@functools.partial(
    jax.jit, static_argnames=("cfg", "mesh", "num_rounds", "Eb")
)
def _run_sharded(state, arrays, halo, cfg, mesh, num_rounds, Eb):
    state_specs = jax.tree.map(_spec, state)
    plan_specs = jax.tree.map(_spec, arrays)
    halo_specs = jax.tree.map(lambda x: P(), halo)

    def body(st_s, pl_s, halo_t):
        st = jax.tree.map(lambda x: x[0], st_s)
        pl = jax.tree.map(lambda x: x[0], pl_s)

        def step(s, _):
            return _local_round(s, pl, halo_t, cfg, Eb), None

        st, _ = jax.lax.scan(step, st, None, length=num_rounds)
        return jax.tree.map(lambda x: x[None], st)

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs, plan_specs, halo_specs),
        out_specs=state_specs,
        check_vma=False,
    )
    return fn(state, arrays, halo)


def run_rounds_sharded(
    state: FlowUpdatingState,
    plan: ShardPlan,
    cfg: RoundConfig,
    mesh: jax.sharding.Mesh,
    num_rounds: int,
    arrays: tuple[PlanArrays, HaloTables] | None = None,
) -> FlowUpdatingState:
    """Run ``num_rounds`` sharded rounds as one compiled shard_map'd scan."""
    if cfg.needs_coloring:
        raise NotImplementedError(
            "fast synchronous pairwise reads the remote endpoint's estimate; "
            "use the GSPMD path (flow_updating_tpu.parallel.auto) for it"
        )
    if arrays is None:
        arrays = plan_device_arrays(plan, mesh)
    plan_arrays, halo = arrays
    return _run_sharded(state, plan_arrays, halo, cfg, mesh, num_rounds, plan.Eb)


def gather_estimates(state: FlowUpdatingState, plan: ShardPlan) -> np.ndarray:
    """Per-node estimates in *global* node order (host-side)."""
    S, Nb, Eb, N = plan.num_shards, plan.Nb, plan.Eb, plan.topo.num_nodes
    flow = np.asarray(state.flow)
    value = np.asarray(state.value)
    src = np.asarray(plan.arrays.src_local)
    sums = np.zeros((S, Nb), flow.dtype)
    for s in range(S):
        np.add.at(sums[s], src[s], flow[s])
    est = value - sums
    return est[:, : plan.cap].reshape(-1)[:N].copy()


def gather_node_array(x, plan: ShardPlan) -> np.ndarray:
    """Unpad a (S, Nb)-stacked per-node array back to global (N,) order."""
    N = plan.topo.num_nodes
    return np.asarray(x)[:, : plan.cap].reshape(-1)[:N].copy()
