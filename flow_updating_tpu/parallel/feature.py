"""Feature-axis model parallelism: shard the D payload lanes across
devices.

A D-feature run is exactly D independent scalar protocol instances
sharing ONE control plane — firing decisions, delivery masks, drop
draws and liveness are feature-free (models/state.py, pinned
bit-for-bit by tests/test_vector_payload.py).  That makes the feature
dimension the perfect model-parallel axis: shard every payload leaf's
trailing feature axis over the mesh's ``'feature'`` axis, REPLICATE the
control plane, and each device runs the unmodified round kernel on its
``D / S_f`` feature slice.  No collective ever crosses the feature
axis during gossip — per-device edge traffic drops to ``E * D/S_f``
payload lanes and the shard outputs concatenate to the single-device
run bit-for-bit (drop draws are control state, so even lossy runs
agree positionally).

Collectives appear in exactly two places, both outside the round scan:

* the trainer's logits ``z = sum_d X[..., d] w[..., d]`` reduce over
  features — one ``psum`` over ``'feature'`` per local step
  (:func:`feature_logits`);
* Gossip-PGA's periodic global average reduces over nodes — one
  ``psum`` over ``'nodes'`` per sync (:func:`global_average_feature`),
  the psum-native form of arXiv:2105.09080's H-step sync (no host
  round-trip, composes with the 2-D ``('nodes', 'feature')`` mesh).

The chunked pipelined schedule (models/rounds.py) composes by sharding
the leading chunk axis instead: each device streams its OWN contiguous
chunks, so chunking x feature-sharding multiplies the per-device wire
reduction (``E * c`` lanes per visit, ``n_chunks / S_f`` visits per
pass per device).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flow_updating_tpu.models.config import RoundConfig, RoundParams
from flow_updating_tpu.models.rounds import (
    ChunkedState,
    _CHUNK_LEAVES,
    check_chunked_config,
    node_estimates,
    run_rounds,
    run_rounds_chunked,
)
from flow_updating_tpu.models.state import FlowUpdatingState, _ex
from flow_updating_tpu.parallel.mesh import (
    FEATURE_AXIS,
    NODE_AXIS,
    make_mesh2d,
    shard_map,
)

#: FlowUpdatingState leaves that carry a trailing feature axis in vector
#: mode — the shardable payload planes.  Everything else is the
#: replicated control plane (masks, counters, PRNG key): the protocol's
#: decisions are payload-independent, which is WHY feature sharding
#: needs no round-time collectives.
PAYLOAD_LEAVES = ("value", "flow", "est", "last_avg",
                  "pending_flow", "pending_est", "buf_flow", "buf_est")


def check_feature_mesh(mesh) -> int:
    """Validate that ``mesh`` carries the feature axis; returns S_f."""
    if FEATURE_AXIS not in mesh.axis_names:
        raise ValueError(
            f"mesh axes {mesh.axis_names} lack {FEATURE_AXIS!r}; build "
            "one with parallel.mesh.make_mesh2d(graph, feature)")
    return int(mesh.shape[FEATURE_AXIS])


def _check_features(D: int, sf: int, what: str) -> None:
    if D % sf:
        raise ValueError(
            f"{what}={D} must divide evenly over {sf} feature shards")


def state_feature_specs(state: FlowUpdatingState):
    """Per-leaf PartitionSpecs: payload leaves shard their LAST axis over
    the feature mesh axis, control leaves replicate.  The state must be
    in vector mode (payload leaves carry the trailing feature axis)."""
    if state.value.ndim != 2:
        raise ValueError(
            "feature sharding needs a vector payload: init the state "
            f"with (N, D) values (got value shape {state.value.shape})")
    specs = {}
    for f in state.__dataclass_fields__:
        x = getattr(state, f)
        if f in PAYLOAD_LEAVES:
            specs[f] = P(*([None] * (x.ndim - 1)), FEATURE_AXIS)
        else:
            specs[f] = P()
    return state.replace(**specs)


def chunked_feature_specs(cs: ChunkedState):
    """ChunkedState specs: the chunk-major leaves shard their LEADING
    chunk axis (each device streams its own contiguous chunks); the
    control window replicates.  The window's payload planes are scratch
    (overwritten every visit) — :func:`run_chunked_feature` zeroes them
    on exit so the returned state is deterministic and replicated."""
    window = jax.tree.map(lambda _: P(), cs.state)
    specs = {f: P(FEATURE_AXIS) for f in _CHUNK_LEAVES}
    return cs.replace(state=window, **specs)


def place_feature_state(state: FlowUpdatingState, mesh) -> FlowUpdatingState:
    """Device-place a (host or single-device) vector state onto the
    feature mesh according to :func:`state_feature_specs`."""
    specs = state_feature_specs(state)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, jax.sharding.NamedSharding(mesh, s)),
        state, specs)


@functools.partial(jax.jit, static_argnames=("cfg", "num_rounds", "mesh"))
def run_rounds_feature(
    state: FlowUpdatingState, topo, cfg: RoundConfig, num_rounds: int,
    mesh, params: RoundParams | None = None,
) -> FlowUpdatingState:
    """``num_rounds`` rounds with the payload feature axis sharded over
    ``mesh``'s ``'feature'`` axis — bit-exact vs the single-device
    vector run (lane independence), drop>0 and churn included (the drop
    draws are replicated control state: every shard realizes the same
    per-edge loss pattern, exactly like the single-device run where one
    draw serves all D lanes)."""
    sf = check_feature_mesh(mesh)
    _check_features(state.value.shape[-1], sf, "payload features D")
    if cfg.kernel != "edge":
        raise ValueError("feature sharding runs the edge kernel "
                         "(kernel='edge')")
    if cfg.robust == "trim":
        raise ValueError(
            "robust='trim' is scalar-only (control-plane estimate marks); "
            "vector payloads use robust='clip'")
    specs = state_feature_specs(state)
    arrays_specs = jax.tree.map(lambda _: P(), topo)

    def body(st, ta):
        return run_rounds(st, ta, cfg, num_rounds, params=params)

    fn = shard_map(body, mesh=mesh, in_specs=(specs, arrays_specs),
                   out_specs=specs, check_vma=False)
    return fn(state, topo)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "num_rounds", "rounds_per_visit", "mesh"))
def run_chunked_feature(
    cs: ChunkedState, topo, cfg: RoundConfig, num_rounds: int, mesh,
    rounds_per_visit: int = 1, params: RoundParams | None = None,
) -> ChunkedState:
    """The pipelined chunked schedule with the CHUNK axis sharded over
    the feature mesh axis: each device streams its own ``n_chunks/S_f``
    contiguous chunks, ``num_rounds`` counts each shard's underlying
    rounds (so one call advances every chunk's instance by
    ``num_rounds / n_chunks * S_f`` rounds... i.e. the same per-chunk
    progress as the single-device call with the same ``num_rounds``
    PER PASS accounting — pass ``num_rounds`` as multiples of the LOCAL
    pass length ``(n_chunks / S_f) * rounds_per_visit``).

    Bit-exact per chunk vs the single-device chunked schedule for
    EVERY config, drop>0 included: each chunk's instance carries its
    own round counter, clocks and PRNG key in the chunk-major leaves,
    so its trajectory cannot depend on which device visits it or in
    what order.  The control window is per-visit scratch (plus the
    shared churn masks); the scratch leaves are returned zeroed so the
    declared-replicated output is deterministic."""
    sf = check_feature_mesh(mesh)
    check_chunked_config(cfg, cs.features, cs.chunk)
    _check_features(cs.n_chunks, sf, "n_chunks")
    local_pass = (cs.n_chunks // sf) * rounds_per_visit
    if num_rounds % local_pass:
        raise ValueError(
            f"num_rounds={num_rounds} must be a multiple of the LOCAL "
            f"pass length (n_chunks/S_f)*rounds_per_visit = {local_pass}")
    specs = chunked_feature_specs(cs)
    arrays_specs = jax.tree.map(lambda _: P(), topo)

    def body(c, ta):
        out = run_rounds_chunked(c, ta, cfg, num_rounds,
                                 rounds_per_visit=rounds_per_visit,
                                 params=params)
        # the working window holds whichever chunk this shard visited
        # last — shard-divergent scratch.  Zero every windowed leaf
        # (the shared churn masks stay) so the declared-replicated
        # output is really replicated.
        win = out.state.replace(**{
            f: jnp.zeros_like(getattr(out.state, f))
            for f in _CHUNK_LEAVES})
        return out.replace(state=win)

    fn = shard_map(body, mesh=mesh, in_specs=(specs, arrays_specs),
                   out_specs=specs, check_vma=False)
    return fn(cs, topo)


# ---- the trainer's two cross-shard reductions ---------------------------


def feature_logits(X, w):
    """Per-node logits under feature sharding: the local partial
    ``sum_d X[n, m, d] w[n, d]`` psum-reduced over the feature axis —
    the ONE cross-feature collective of the gossip-SGD local step.
    Call inside a feature shard_map with X, w feature-sharded."""
    z = jnp.einsum("nmd,nd->nm", X, w)
    return jax.lax.psum(z, FEATURE_AXIS)


def _pga_rebase(state: FlowUpdatingState, topo, node_axis: bool):
    """The PGA value rebase on one shard: estimates to the alive-mean,
    sums psum-reduced over the node axis when it is real."""
    est = node_estimates(state, topo)
    alive = state.alive
    a = _ex(alive, est)
    cnt = jnp.sum(alive)
    tot = jnp.sum(jnp.where(a, est, 0), axis=0)      # (f_local,)
    if node_axis:
        cnt = jax.lax.psum(cnt, NODE_AXIS)
        tot = jax.lax.psum(tot, NODE_AXIS)
    mean = tot / jnp.maximum(cnt, 1).astype(est.dtype)
    value = jnp.where(a, state.value - est + mean, state.value)
    return state.replace(value=value)


@functools.partial(jax.jit, static_argnames=("mesh",))
def global_average_feature(state: FlowUpdatingState, topo,
                           mesh) -> FlowUpdatingState:
    """Gossip-PGA's periodic global average as a native collective
    (arXiv:2105.09080): every alive node's estimate is rebased to the
    exact alive-mean via the mass-preserving ``value <- value - est +
    mean(est)`` — computed entirely device-side under the 2-D mesh.
    The node-sum rides ``psum('nodes')`` (identity when the graph axis
    is trivial); the feature axis needs NO collective (each shard owns
    its features' mean outright) — the whole sync is one psum instead
    of a host gather/scatter round-trip."""
    check_feature_mesh(mesh)
    specs = state_feature_specs(state)
    arrays_specs = jax.tree.map(lambda _: P(), topo)
    node_axis = (NODE_AXIS in mesh.axis_names
                 and int(mesh.shape[NODE_AXIS]) > 1)

    fn = shard_map(lambda st, ta: _pga_rebase(st, ta, node_axis),
                   mesh=mesh, in_specs=(specs, arrays_specs),
                   out_specs=specs, check_vma=False)
    return fn(state, topo)


def feature_mesh(feature_shards: int, graph_shards: int = 1):
    """Convenience: the ``('nodes', 'feature')`` mesh for S_f payload
    shards (re-exported so workloads never import mesh internals)."""
    return make_mesh2d(graph_shards, feature_shards)
