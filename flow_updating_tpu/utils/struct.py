"""Frozen pytree dataclasses over ``jax.tree_util.register_dataclass``.

The repo's state/plan containers need exactly two things: a frozen
dataclass registered as a JAX pytree, and per-field control over whether
a field is traced data (a leaf subtree) or static metadata (hashed into
the treedef, e.g. routing plans and color counts).  ``flax.struct``
provides this surface, but pulling in flax for two decorators broke the
package's install contract — ``pyproject.toml`` and README promise jax +
numpy as the only hard dependencies, mirroring the reference's two-line
``requirements.txt`` (/root/reference/requirements.txt:1-2), yet six
modules imported an undeclared package (VERDICT r4 weak #6).  This is
the same surface implemented on jax's own registry; semantics match
``flax.struct.dataclass`` for everything the repo uses:

- fields are pytree data by default; ``field(pytree_node=False)`` makes
  a field static metadata (kept out of tracing, part of the jit cache
  key via the treedef, exactly like flax's aux data);
- instances are immutable; ``obj.replace(**updates)`` and
  ``dataclasses.replace(obj, ...)`` both produce updated copies.
"""

from __future__ import annotations

import dataclasses

import jax


def field(pytree_node: bool = True, **kwargs):
    """``dataclasses.field`` carrying the data-vs-metadata marker."""
    metadata = dict(kwargs.pop("metadata", None) or {})
    metadata["pytree_node"] = pytree_node
    return dataclasses.field(metadata=metadata, **kwargs)


def dataclass(cls):
    """Frozen dataclass registered as a pytree node.

    Fields marked ``field(pytree_node=False)`` become static treedef
    metadata; everything else is traced data.
    """
    cls = dataclasses.dataclass(frozen=True)(cls)
    data_fields = [f.name for f in dataclasses.fields(cls)
                   if f.metadata.get("pytree_node", True)]
    meta_fields = [f.name for f in dataclasses.fields(cls)
                   if not f.metadata.get("pytree_node", True)]
    jax.tree_util.register_dataclass(
        cls, data_fields=data_fields, meta_fields=meta_fields)

    def replace(self, **updates):
        return dataclasses.replace(self, **updates)

    cls.replace = replace
    return cls
