"""Backend pinning for environments that tunnel JAX at a single TPU chip.

The ambient environment registers an ``axon`` TPU plugin through a
sitecustomize hook that imports jax at interpreter startup, so CPU-only
work (tests, virtual multi-device meshes, benchmark fallbacks) must both
force the CPU platform *and* deregister the TPU plugin factories before
any backend initializes — otherwise the process contends for (and can
hang on) the one tunneled chip.  This module is the single home of that
ordering-sensitive recipe; tests/conftest.py, the CLI ``--backend cpu``
path, and bench.py's CPU fallback all share it.
"""

from __future__ import annotations

import os


def _with_host_device_count(flags: str, n: int) -> str:
    kept = [
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    ]
    kept.append(f"--xla_force_host_platform_device_count={int(n)}")
    return " ".join(kept)


def cpu_subprocess_env(
    n_virtual_devices: int | None = None,
    extra_path: str | None = None,
) -> dict:
    """Environment for a *subprocess* that must come up CPU-only.

    A fresh interpreter needs no factory deregistration — dropping the axon
    sitecustomize entry from PYTHONPATH means the TPU plugin never
    registers.  ``extra_path`` (e.g. the repo root) is prepended so the
    child can still import this package.
    """
    env = dict(os.environ)
    keep = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon_site" not in p
    ]
    if extra_path:
        keep.insert(0, extra_path)
    env["PYTHONPATH"] = os.pathsep.join(keep)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    if n_virtual_devices:
        env["XLA_FLAGS"] = _with_host_device_count(
            env.get("XLA_FLAGS", ""), n_virtual_devices
        )
    return env


def pin_cpu(n_virtual_devices: int | None = None) -> None:
    """Force the host-CPU backend, optionally with N virtual devices.

    Must run before any JAX backend initializes (env vars are read lazily
    at first backend init, so calling this after ``import jax`` — but
    before any ``jax.devices()``/trace — is still in time).
    """
    if n_virtual_devices:
        os.environ["XLA_FLAGS"] = _with_host_device_count(
            os.environ.get("XLA_FLAGS", ""), n_virtual_devices
        )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("JAX_PLATFORM_NAME", None)

    import jax

    jax.config.update("jax_platforms", "cpu")

    # pallas (via checkify) registers TPU lowering rules at import time and
    # refuses once "tpu" is deregistered — import it BEFORE the pops.
    import jax.experimental.pallas  # noqa: F401
    import jax._src.xla_bridge as xb

    for plugin in ("axon", "tpu"):
        xb._backend_factories.pop(plugin, None)
