"""Structured JSONL event log — the framework's XBT-logging equivalent.

The reference logs through SimGrid's XBT: timestamped, actor-attributed
text lines (``this_actor.info/error``, ``flowupdating-collectall.py:67,96``)
plus the watcher's periodic ``global_values`` dump (``:134-136``).  Here the
analogous channel is machine-readable: one JSON object per line, each
carrying the simulated round ``t`` and an event ``kind``, written by the
host (watcher samples, engine lifecycle) or streamed out of a compiled run
via :func:`flow_updating_tpu.models.rounds.run_rounds_streamed`.
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO

#: Arrays up to this many elements are inlined as JSON lists; larger ones
#: are summarized (shape + dtype) — an event line is a log record, not a
#: tensor store.
MAX_INLINE_ARRAY = 64


def _jsonable(v):
    """JSON-safe coercion of one emitted field.

    Only 0-d / size-1 array-likes collapse to a Python scalar (``.item()``
    on anything bigger raises); small arrays become lists, large ones a
    shape/dtype stub.  Containers recurse so a dict-valued field (e.g. a
    nested report) with array leaves still serializes."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    shape = getattr(v, "shape", None)
    if shape is not None:  # numpy / jax array-like
        size = 1
        for d in shape:
            size *= int(d)
        if size <= 1:
            return v.item() if size == 1 else []
        if size <= MAX_INLINE_ARRAY:
            return _jsonable(v.tolist())
        return {"__array__": True, "shape": [int(d) for d in shape],
                "dtype": str(getattr(v, "dtype", "?"))}
    if hasattr(v, "item"):  # shapeless scalar wrappers
        return v.item()
    return v


class EventLog:
    """Append-only JSONL sink.  Thread-safe (debug callbacks may fire from
    runtime threads)."""

    def __init__(self, path_or_file: str | IO):
        if isinstance(path_or_file, str):
            self._fh = open(path_or_file, "a", buffering=1)
            self._owns = True
        else:
            self._fh = path_or_file
            self._owns = False
        self._lock = threading.Lock()
        self._t0 = time.monotonic()

    def emit(self, kind: str, **fields) -> None:
        record = {"kind": kind, "wall_s": round(time.monotonic() - self._t0, 6)}
        for k, v in fields.items():
            record[k] = _jsonable(v)
        with self._lock:
            self._fh.write(json.dumps(record, default=str) + "\n")

    def close(self) -> None:
        if self._owns:
            self._fh.close()

    def __enter__(self) -> EventLog:
        return self

    def __exit__(self, *exc) -> None:
        self.close()
