"""Structured JSONL event log — the framework's XBT-logging equivalent.

The reference logs through SimGrid's XBT: timestamped, actor-attributed
text lines (``this_actor.info/error``, ``flowupdating-collectall.py:67,96``)
plus the watcher's periodic ``global_values`` dump (``:134-136``).  Here the
analogous channel is machine-readable: one JSON object per line, each
carrying the simulated round ``t`` and an event ``kind``, written by the
host (watcher samples, engine lifecycle) or streamed out of a compiled run
via :func:`flow_updating_tpu.models.rounds.run_rounds_streamed`.
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO


class EventLog:
    """Append-only JSONL sink.  Thread-safe (debug callbacks may fire from
    runtime threads)."""

    def __init__(self, path_or_file: str | IO):
        if isinstance(path_or_file, str):
            self._fh = open(path_or_file, "a", buffering=1)
            self._owns = True
        else:
            self._fh = path_or_file
            self._owns = False
        self._lock = threading.Lock()
        self._t0 = time.monotonic()

    def emit(self, kind: str, **fields) -> None:
        record = {"kind": kind, "wall_s": round(time.monotonic() - self._t0, 6)}
        for k, v in fields.items():
            if hasattr(v, "item"):  # 0-d numpy / jax scalars
                v = v.item()
            record[k] = v
        with self._lock:
            self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        if self._owns:
            self._fh.close()

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
