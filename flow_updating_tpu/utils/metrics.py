"""Convergence and invariant metrics.

The reference's only observability is the watcher's periodic dump of every
peer's ``value``/``last_avg`` (``flowupdating-collectall.py:131-148``), with
convergence judged by eye against the true mean.  Here the same quantities
are first-class metrics, plus the protocol invariants the paper guarantees:

* **mass conservation** — with antisymmetric flows the global sum of node
  estimates equals the sum of inputs.  In-flight (sent, undelivered)
  messages perturb it transiently; after a synchronous delivery it is exact.
* **flow antisymmetry** — ``flow[e] == -flow[rev[e]]`` for every edge pair
  whose latest messages have been delivered (the ``flows[sender] =
  -msg.flow`` write, reference ``:99``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.rounds import node_estimates


def rmse(estimates, true_mean) -> jnp.ndarray:
    err = estimates - true_mean
    return jnp.sqrt(jnp.mean(err * err))


def mass_residual(state, topo) -> jnp.ndarray:
    """sum(current estimates) - sum(inputs); ~0 in quiescent/synchronous
    states, transiently nonzero while messages are in flight.

    Vector payloads get the PER-FEATURE residual (shape ``(D,)``): summing
    across features first would let a +x error in one feature hide a -x
    error in another.  Scalar states keep the 0-d result."""
    est = node_estimates(state, topo)
    return jnp.sum(est, axis=0) - jnp.sum(state.value, axis=0)


def summarize_mass_residual(res):
    """Report form of a mass residual: a plain float for scalar payloads,
    ``{"max": max|r_d|, "mean": mean(r_d), "per_feature": [...]}`` for a
    ``(D,)`` per-feature residual (per-feature list included up to 64
    features)."""
    r = np.asarray(res)
    if r.ndim == 0:
        return float(r)
    out = {"max": float(np.max(np.abs(r))) if r.size else 0.0,
           "mean": float(np.mean(r)) if r.size else 0.0}
    if r.size <= 64:
        out["per_feature"] = [float(x) for x in r]
    return out


def antisymmetry_residual(state, topo) -> jnp.ndarray:
    """max |flow[e] + flow[rev[e]]| over edges."""
    return jnp.max(jnp.abs(state.flow + state.flow[topo.rev]))


def observer_sample(t, rmse_v, max_abs_err, mass, fired_total) -> dict:
    """The streamed-observer emit record — ONE shape for every execution
    mode (node kernel's debug-callback sampler, the halo engine branch,
    the pod-sharded kernel), so the watcher contract can't drift between
    copies.  All inputs host scalars."""
    return {
        "t": int(t),
        "rmse": float(rmse_v),
        "max_abs_err": float(max_abs_err),
        "mass": float(mass),
        "fired_total": int(fired_total),
    }


def convergence_report(state, topo, true_mean) -> dict:
    est = node_estimates(state, topo)
    err = est - jnp.asarray(true_mean, est.dtype)
    return {
        "t": int(state.t),
        "rmse": float(jnp.sqrt(jnp.mean(err * err))),
        "max_abs_err": float(jnp.max(jnp.abs(err))),
        # per-feature for vector payloads (summary dict), float for scalar
        "mass_residual": summarize_mass_residual(
            jnp.sum(est, axis=0) - jnp.sum(state.value, axis=0)
        ),
        "antisymmetry_residual": float(
            jnp.max(jnp.abs(state.flow + state.flow[topo.rev]))
        ),
    }
