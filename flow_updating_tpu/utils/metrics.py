"""Convergence and invariant metrics.

The reference's only observability is the watcher's periodic dump of every
peer's ``value``/``last_avg`` (``flowupdating-collectall.py:131-148``), with
convergence judged by eye against the true mean.  Here the same quantities
are first-class metrics, plus the protocol invariants the paper guarantees:

* **mass conservation** — with antisymmetric flows the global sum of node
  estimates equals the sum of inputs.  In-flight (sent, undelivered)
  messages perturb it transiently; after a synchronous delivery it is exact.
* **flow antisymmetry** — ``flow[e] == -flow[rev[e]]`` for every edge pair
  whose latest messages have been delivered (the ``flows[sender] =
  -msg.flow`` write, reference ``:99``).
"""

from __future__ import annotations

import jax.numpy as jnp

from flow_updating_tpu.models.rounds import node_estimates


def rmse(estimates, true_mean) -> jnp.ndarray:
    err = estimates - true_mean
    return jnp.sqrt(jnp.mean(err * err))


def mass_residual(state, topo) -> jnp.ndarray:
    """sum(current estimates) - sum(inputs); ~0 in quiescent/synchronous
    states, transiently nonzero while messages are in flight."""
    est = node_estimates(state, topo)
    return jnp.sum(est) - jnp.sum(state.value)


def antisymmetry_residual(state, topo) -> jnp.ndarray:
    """max |flow[e] + flow[rev[e]]| over edges."""
    return jnp.max(jnp.abs(state.flow + state.flow[topo.rev]))


def observer_sample(t, rmse_v, max_abs_err, mass, fired_total) -> dict:
    """The streamed-observer emit record — ONE shape for every execution
    mode (node kernel's debug-callback sampler, the halo engine branch,
    the pod-sharded kernel), so the watcher contract can't drift between
    copies.  All inputs host scalars."""
    return {
        "t": int(t),
        "rmse": float(rmse_v),
        "max_abs_err": float(max_abs_err),
        "mass": float(mass),
        "fired_total": int(fired_total),
    }


def convergence_report(state, topo, true_mean) -> dict:
    est = node_estimates(state, topo)
    err = est - jnp.asarray(true_mean, est.dtype)
    return {
        "t": int(state.t),
        "rmse": float(jnp.sqrt(jnp.mean(err * err))),
        "max_abs_err": float(jnp.max(jnp.abs(err))),
        "mass_residual": float(jnp.sum(est) - jnp.sum(state.value)),
        "antisymmetry_residual": float(
            jnp.max(jnp.abs(state.flow + state.flow[topo.rev]))
        ),
    }
