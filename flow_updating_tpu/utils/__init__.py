from flow_updating_tpu.utils.metrics import (
    rmse,
    mass_residual,
    antisymmetry_residual,
    convergence_report,
)

__all__ = [
    "rmse",
    "mass_residual",
    "antisymmetry_residual",
    "convergence_report",
]
