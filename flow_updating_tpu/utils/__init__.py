from flow_updating_tpu.utils.metrics import (
    rmse,
    mass_residual,
    antisymmetry_residual,
    convergence_report,
)
from flow_updating_tpu.utils.checkpoint import (
    save_checkpoint,
    load_checkpoint,
    topology_fingerprint,
)
from flow_updating_tpu.utils.eventlog import EventLog
from flow_updating_tpu.utils.trace import trace, annotate

__all__ = [
    "rmse",
    "mass_residual",
    "antisymmetry_residual",
    "convergence_report",
    "save_checkpoint",
    "load_checkpoint",
    "topology_fingerprint",
    "EventLog",
    "trace",
    "annotate",
]
