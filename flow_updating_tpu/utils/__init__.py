"""Utility subpackage: metrics, checkpointing, event log, tracing, struct.

Re-exports are lazy (PEP 562): ``utils.checkpoint`` imports the model
state (which imports the topology, which imports ``utils.struct``), so an
eager re-export here would close an import cycle for any module that
pulls a utility in at its own import time.  Lazy resolution also keeps
light entry points (``utils.backend`` is imported before backend
selection) from paying for jax-heavy siblings.
"""

_EXPORTS = {
    "rmse": "metrics",
    "mass_residual": "metrics",
    "antisymmetry_residual": "metrics",
    "convergence_report": "metrics",
    "save_checkpoint": "checkpoint",
    "load_checkpoint": "checkpoint",
    "topology_fingerprint": "checkpoint",
    "EventLog": "eventlog",
    "trace": "trace",
    "annotate": "trace",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"flow_updating_tpu.utils.{_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
