"""Checkpoint / resume.

The reference has no checkpointing at all (SURVEY.md §5): a run's full state
lives in per-actor Python attributes (``value``, ``flows``, ``estimates``,
timers — ``flowupdating-collectall.py:26-45``) and dies with the process.
Here the whole simulation state is one :class:`FlowUpdatingState` pytree, so
checkpointing is a flat archive of named arrays plus a manifest:

* every pytree leaf, fetched to host and stored in one compressed ``.npz``;
* the :class:`RoundConfig` (all static knobs) as JSON;
* a topology fingerprint (node/edge counts + content digest of the edge list,
  delays and initial values), verified at restore so a checkpoint can never
  be resumed against a different graph.

Sharded states (leaves with a leading shard axis, or GSPMD-placed global
arrays) round-trip transparently: ``np.asarray`` gathers to host at save;
the caller re-places the restored state on its mesh (``shard_state`` /
``init_plan_state``-style placement) after load.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import warnings

import jax
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState

# 2: pending_* mailbox arrays gained a leading depth axis (Q, E) and the
#    pending_stamp field (models/state.py) — v1 checkpoints cannot resume.
FORMAT_VERSION = 2


def _state_classes() -> dict:
    from flow_updating_tpu.models.sync import NodeSyncState

    return {
        "FlowUpdatingState": FlowUpdatingState,
        "NodeSyncState": NodeSyncState,
    }


def topology_fingerprint(topo) -> dict:
    """Cheap content digest binding a checkpoint to its graph."""
    h = hashlib.sha256()
    for arr in (topo.src, topo.dst, topo.delay, topo.values):
        a = np.ascontiguousarray(arr)
        h.update(a.tobytes())
    return {
        "num_nodes": int(topo.num_nodes),
        "num_edges": int(topo.num_edges),
        "digest": h.hexdigest(),
    }


def save_checkpoint(
    path: str,
    state: FlowUpdatingState,
    cfg: RoundConfig,
    topo=None,
    extra: dict | None = None,
) -> None:
    """Write one atomic checkpoint file (``.npz``) at ``path``.

    If the topology has a computed edge coloring cached (the fast-pairwise
    prerequisite — minutes-scale on degree-skewed graphs at 100k+ nodes,
    see Topology.edge_coloring), it rides along and is re-seeded on
    restore, so a resumed run never recolors.
    """
    arrays = {}
    for name in state.__dataclass_fields__:
        leaf = getattr(state, name)
        arrays[f"state.{name}"] = np.asarray(jax.device_get(leaf))
    coloring = getattr(topo, "_edge_coloring", None) if topo is not None \
        else None
    if coloring is not None:
        arrays["aux.edge_color"] = coloring[0]
    manifest = {
        "format_version": FORMAT_VERSION,
        "state_class": type(state).__name__,
        "config": dataclasses.asdict(cfg),
        "topology": topology_fingerprint(topo) if topo is not None else None,
        "dtypes": {k[len("state."):]: str(v.dtype)
                   for k, v in arrays.items() if k.startswith("state.")},
        "num_colors": coloring[1] if coloring is not None else None,
        "extra": extra or {},
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f, __manifest__=np.frombuffer(
                json.dumps(manifest).encode(), dtype=np.uint8
            ), **arrays,
        )
    os.replace(tmp, path)


def load_checkpoint(
    path: str,
    topo=None,
) -> tuple[FlowUpdatingState, RoundConfig, dict]:
    """Read a checkpoint.  Returns ``(state, config, extra)``.

    If ``topo`` is given and the checkpoint carries a fingerprint, they must
    match — a checkpoint can never be resumed against a different graph.
    """
    with np.load(path) as z:
        manifest = json.loads(bytes(z["__manifest__"]).decode())
        if manifest["format_version"] != FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {manifest['format_version']} != "
                f"{FORMAT_VERSION}"
            )
        fields = {}
        aux_color = None
        for key in z.files:
            if key.startswith("state."):
                fields[key[len("state."):]] = z[key]
            elif key == "aux.edge_color":
                aux_color = z[key]
    cls_name = manifest.get("state_class", "FlowUpdatingState")
    classes = _state_classes()
    if cls_name not in classes:
        raise ValueError(f"unknown checkpoint state class {cls_name!r}")
    state_cls = classes[cls_name]
    want = set(state_cls.__dataclass_fields__)
    have = set(fields)
    if have != want:
        raise ValueError(
            f"checkpoint fields mismatch: missing {sorted(want - have)}, "
            f"unexpected {sorted(have - want)}"
        )
    if topo is not None and manifest.get("topology"):
        fp = topology_fingerprint(topo)
        if fp != manifest["topology"]:
            raise ValueError(
                "checkpoint was taken on a different topology "
                f"(saved {manifest['topology']['num_nodes']} nodes/"
                f"{manifest['topology']['num_edges']} edges, have "
                f"{fp['num_nodes']}/{fp['num_edges']}, digests "
                f"{'match' if fp['digest'] == manifest['topology']['digest'] else 'differ'})"
            )
        # re-seed the cached edge coloring (fingerprint-validated, so it
        # is guaranteed to describe this exact edge list)
        if aux_color is not None and manifest.get("num_colors") is not None:
            object.__setattr__(
                topo, "_edge_coloring",
                (aux_color, int(manifest["num_colors"])),
            )
    cfg = RoundConfig(**manifest["config"])

    # Dtype validation: a checkpoint saved under x64 (float64/int64 leaves)
    # restored in an x64-disabled runtime would be *silently* downcast to
    # 32-bit the moment the numpy leaves enter jit, quietly changing
    # trajectories while claiming a bit-exact resume.  Detect that here and
    # make the cast loud and explicit instead.
    saved_dtypes = manifest.get("dtypes", {})
    for name, arr in fields.items():
        saved = saved_dtypes.get(name)
        if saved is not None and str(arr.dtype) != saved:
            raise ValueError(
                f"checkpoint leaf {name!r} dtype {arr.dtype} does not match "
                f"its manifest entry {saved!r} (corrupt archive?)"
            )
        canonical = jax.dtypes.canonicalize_dtype(arr.dtype)
        if canonical != arr.dtype:
            warnings.warn(
                f"checkpoint leaf {name!r} was saved as {arr.dtype} but this "
                f"runtime canonicalizes it to {canonical} (jax_enable_x64 is "
                "off) — casting explicitly; the resume is NOT bit-exact",
                stacklevel=2,
            )
            fields[name] = arr.astype(canonical)

    state = state_cls(**fields)
    return state, cfg, manifest.get("extra", {})
