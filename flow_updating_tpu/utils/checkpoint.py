"""Checkpoint / resume.

The reference has no checkpointing at all (SURVEY.md §5): a run's full state
lives in per-actor Python attributes (``value``, ``flows``, ``estimates``,
timers — ``flowupdating-collectall.py:26-45``) and dies with the process.
Here the whole simulation state is one :class:`FlowUpdatingState` pytree, so
checkpointing is a flat archive of named arrays plus a manifest:

* every pytree leaf, fetched to host and stored in one compressed ``.npz``;
* the :class:`RoundConfig` (all static knobs) as JSON;
* a topology fingerprint (node/edge counts + content digest of the edge list,
  delays and initial values), verified at restore so a checkpoint can never
  be resumed against a different graph.

Sharded states (leaves with a leading shard axis, or GSPMD-placed global
arrays) round-trip transparently: ``np.asarray`` gathers to host at save;
the caller re-places the restored state on its mesh (``shard_state`` /
``init_plan_state``-style placement) after load.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import warnings

import jax
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState

# 2: pending_* mailbox arrays gained a leading depth axis (Q, E) and the
#    pending_stamp field (models/state.py) — v1 checkpoints cannot resume.
FORMAT_VERSION = 2


def _state_classes() -> dict:
    from flow_updating_tpu.models.sync import NodeSyncState

    return {
        "FlowUpdatingState": FlowUpdatingState,
        "NodeSyncState": NodeSyncState,
    }


def topology_fingerprint(topo) -> dict:
    """Cheap content digest binding a checkpoint to its graph."""
    h = hashlib.sha256()
    for arr in (topo.src, topo.dst, topo.delay, topo.values):
        a = np.ascontiguousarray(arr)
        h.update(a.tobytes())
    return {
        "num_nodes": int(topo.num_nodes),
        "num_edges": int(topo.num_edges),
        "digest": h.hexdigest(),
    }


def _write_archive(path: str, manifest: dict, arrays: dict) -> None:
    """Single durability-critical write path for every checkpoint flavor:
    compressed npz with the JSON manifest as a uint8 buffer, written to a
    pid-suffixed temp file and atomically renamed."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f, __manifest__=np.frombuffer(
                json.dumps(manifest).encode(), dtype=np.uint8
            ), **arrays,
        )
    os.replace(tmp, path)


def _read_manifest(z) -> dict:
    manifest = json.loads(bytes(z["__manifest__"]).decode())
    if manifest["format_version"] != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format_version']} != "
            f"{FORMAT_VERSION}")
    return manifest


def save_checkpoint(
    path: str,
    state: FlowUpdatingState,
    cfg: RoundConfig,
    topo=None,
    extra: dict | None = None,
) -> None:
    """Write one atomic checkpoint file (``.npz``) at ``path``.

    If the topology has a computed edge coloring cached (the fast-pairwise
    prerequisite — minutes-scale on degree-skewed graphs at 100k+ nodes,
    see Topology.edge_coloring), it rides along and is re-seeded on
    restore, so a resumed run never recolors.
    """
    arrays = {}
    for name in state.__dataclass_fields__:
        leaf = getattr(state, name)
        arrays[f"state.{name}"] = np.asarray(jax.device_get(leaf))
    coloring = getattr(topo, "_edge_coloring", None) if topo is not None \
        else None
    if coloring is not None:
        arrays["aux.edge_color"] = coloring[0]
    manifest = {
        "format_version": FORMAT_VERSION,
        "state_class": type(state).__name__,
        "config": dataclasses.asdict(cfg),
        "topology": topology_fingerprint(topo) if topo is not None else None,
        "dtypes": {k[len("state."):]: str(v.dtype)
                   for k, v in arrays.items() if k.startswith("state.")},
        "num_colors": coloring[1] if coloring is not None else None,
        "extra": extra or {},
    }
    _write_archive(path, manifest, arrays)


def load_checkpoint(
    path: str,
    topo=None,
) -> tuple[FlowUpdatingState, RoundConfig, dict]:
    """Read a checkpoint.  Returns ``(state, config, extra)``.

    If ``topo`` is given and the checkpoint carries a fingerprint, they must
    match — a checkpoint can never be resumed against a different graph.
    """
    with np.load(path) as z:
        manifest = _read_manifest(z)
        fields = {}
        aux_color = None
        for key in z.files:
            if key.startswith("state."):
                fields[key[len("state."):]] = z[key]
            elif key == "aux.edge_color":
                aux_color = z[key]
    cls_name = manifest.get("state_class", "FlowUpdatingState")
    classes = _state_classes()
    if cls_name not in classes:
        raise ValueError(f"unknown checkpoint state class {cls_name!r}")
    state_cls = classes[cls_name]
    want = set(state_cls.__dataclass_fields__)
    have = set(fields)
    if have != want:
        raise ValueError(
            f"checkpoint fields mismatch: missing {sorted(want - have)}, "
            f"unexpected {sorted(have - want)}"
        )
    if topo is not None and manifest.get("topology"):
        fp = topology_fingerprint(topo)
        if fp != manifest["topology"]:
            raise ValueError(
                "checkpoint was taken on a different topology "
                f"(saved {manifest['topology']['num_nodes']} nodes/"
                f"{manifest['topology']['num_edges']} edges, have "
                f"{fp['num_nodes']}/{fp['num_edges']}, digests "
                f"{'match' if fp['digest'] == manifest['topology']['digest'] else 'differ'})"
            )
        # re-seed the cached edge coloring (fingerprint-validated, so it
        # is guaranteed to describe this exact edge list)
        if aux_color is not None and manifest.get("num_colors") is not None:
            object.__setattr__(
                topo, "_edge_coloring",
                (aux_color, int(manifest["num_colors"])),
            )
    cfg = RoundConfig(**manifest["config"])

    # Dtype validation: a checkpoint saved under x64 (float64/int64 leaves)
    # restored in an x64-disabled runtime would be *silently* downcast to
    # 32-bit the moment the numpy leaves enter jit, quietly changing
    # trajectories while claiming a bit-exact resume.  Detect that here and
    # make the cast loud and explicit instead.
    saved_dtypes = manifest.get("dtypes", {})
    for name, arr in fields.items():
        saved = saved_dtypes.get(name)
        if saved is not None and str(arr.dtype) != saved:
            raise ValueError(
                f"checkpoint leaf {name!r} dtype {arr.dtype} does not match "
                f"its manifest entry {saved!r} (corrupt archive?)"
            )
        canonical = jax.dtypes.canonicalize_dtype(arr.dtype)
        if canonical != arr.dtype:
            warnings.warn(
                f"checkpoint leaf {name!r} was saved as {arr.dtype} but this "
                f"runtime canonicalizes it to {canonical} (jax_enable_x64 is "
                "off) — casting explicitly; the resume is NOT bit-exact",
                stacklevel=2,
            )
            fields[name] = arr.astype(canonical)

    state = state_cls(**fields)
    return state, cfg, manifest.get("extra", {})


# ---- VectorActor carries (user-defined pytrees) -------------------------
#
# A custom actor's state is an arbitrary pytree, so the archive keys are
# the jax keystr paths of its leaves, and restore is TEMPLATE-based: the
# caller passes a freshly-initialized carry from the SAME actor code, and
# every template leaf is filled from the archive (exact key-set, shape
# and dtype match required).  This binds a checkpoint to the actor's
# current structure the same way the fingerprint binds it to the graph —
# a protocol change between save and restore fails loudly instead of
# unflattening garbage.

def save_actor_checkpoint(path, carry, actor_name: str, topo=None,
                          extra: dict | None = None) -> None:
    import jax.tree_util as jtu

    leaves = jtu.tree_leaves_with_path(carry)
    arrays = {}
    for kp, v in leaves:
        arrays[f"leaf{jtu.keystr(kp)}"] = np.asarray(jax.device_get(v))
    manifest = {
        "format_version": FORMAT_VERSION,
        "state_class": "ActorCarry",
        "actor": actor_name,
        "topology": topology_fingerprint(topo) if topo is not None else None,
        "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
        "extra": extra or {},
    }
    _write_archive(path, manifest, arrays)


def load_actor_checkpoint(path, template, actor_name: str, topo=None):
    """Restore a carry saved by :func:`save_actor_checkpoint`.

    ``template``: a freshly-initialized carry from the same actor on the
    same topology — its structure defines what the archive must contain.
    Returns ``(carry, extra)``; leaves keep the template's device
    placement (sharded templates re-place restored leaves).
    """
    import jax.tree_util as jtu

    with np.load(path) as z:
        manifest = _read_manifest(z)
        if manifest.get("state_class") != "ActorCarry":
            raise ValueError(
                f"not a VectorActor checkpoint "
                f"(state_class={manifest.get('state_class')!r})")
        if manifest.get("actor") != actor_name:
            raise ValueError(
                f"checkpoint was saved by actor {manifest.get('actor')!r}, "
                f"restoring under {actor_name!r}")
        saved = {k: z[k] for k in z.files if k.startswith("leaf")}
    if topo is not None and manifest.get("topology"):
        fp = topology_fingerprint(topo)
        if fp != manifest["topology"]:
            raise ValueError(
                "actor checkpoint was taken on a different topology")

    paths, treedef = jtu.tree_flatten_with_path(template)
    want = {f"leaf{jtu.keystr(kp)}" for kp, _ in paths}
    if want != set(saved):
        raise ValueError(
            "actor checkpoint structure does not match the current "
            f"actor's init: missing {sorted(want - set(saved))}, "
            f"unexpected {sorted(set(saved) - want)} (the protocol "
            "changed since the save?)")
    saved_dtypes = manifest.get("dtypes", {})
    leaves = []
    for kp, tleaf in paths:
        key = f"leaf{jtu.keystr(kp)}"
        arr = saved[key]
        # shape/dtype from metadata only — never np.asarray(tleaf): that
        # would gather a sharded template to host (and raise outright on
        # non-fully-addressable multi-process arrays)
        tshape = np.shape(tleaf)
        tdtype = np.dtype(getattr(tleaf, "dtype", np.asarray(tleaf).dtype))
        if arr.shape != tshape:
            raise ValueError(
                f"actor checkpoint leaf {jtu.keystr(kp)} has shape "
                f"{arr.shape}, current actor expects {tshape}")
        man_dtype = saved_dtypes.get(key)
        if man_dtype is not None and str(arr.dtype) != man_dtype:
            raise ValueError(
                f"actor checkpoint leaf {jtu.keystr(kp)} dtype "
                f"{arr.dtype} does not match its manifest entry "
                f"{man_dtype!r} (corrupt archive?)")
        canonical = jax.dtypes.canonicalize_dtype(arr.dtype)
        if canonical != arr.dtype:
            warnings.warn(
                f"actor leaf {jtu.keystr(kp)} saved as {arr.dtype}, "
                f"canonicalized to {canonical} — resume is NOT bit-exact",
                stacklevel=2)
            arr = arr.astype(canonical)
        if np.dtype(canonical) != tdtype:
            raise ValueError(
                f"actor checkpoint leaf {jtu.keystr(kp)} restores as "
                f"{canonical}, but the current actor's init produces "
                f"{tdtype} — the protocol's precision changed since "
                "the save")
        dev = jax.numpy.asarray(arr)
        sh = getattr(tleaf, "sharding", None)
        if sh is not None:
            dev = jax.device_put(dev, sh)
        leaves.append(dev)
    return jtu.tree_unflatten(treedef, leaves), manifest.get("extra", {})
