"""Checkpoint / resume.

The reference has no checkpointing at all (SURVEY.md §5): a run's full state
lives in per-actor Python attributes (``value``, ``flows``, ``estimates``,
timers — ``flowupdating-collectall.py:26-45``) and dies with the process.
Here the whole simulation state is one :class:`FlowUpdatingState` pytree, so
checkpointing is a flat archive of named arrays plus a manifest:

* every pytree leaf, fetched to host and stored in one compressed ``.npz``;
* the :class:`RoundConfig` (all static knobs) as JSON;
* a topology fingerprint (node/edge counts + content digest of the edge list,
  delays and initial values), verified at restore so a checkpoint can never
  be resumed against a different graph.

Sharded states (leaves with a leading shard axis, or GSPMD-placed global
arrays) round-trip transparently: ``np.asarray`` gathers to host at save;
the caller re-places the restored state on its mesh (``shard_state`` /
``init_plan_state``-style placement) after load.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import warnings

import jax
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.state import FlowUpdatingState

# 2: pending_* mailbox arrays gained a leading depth axis (Q, E) and the
#    pending_stamp field (models/state.py) — v1 checkpoints cannot resume.
FORMAT_VERSION = 2

# Service checkpoints (ServiceEngine.save_checkpoint) version their own
# schema on top of the archive format: the dynamic-topology mirror set
# (src/dst/rev/out_deg/rows/delay/free lists/member mask) is part of the
# contract, so adding or renaming one bumps this.
# 2: the meta block may carry the query fabric's lane tables under
#    meta["query"] (QueryFabric.save_checkpoint) — lane -> query
#    bindings, cohorts, free-lane list, admission queue.  Version-1
#    archives (pre-lane) carry no such block and still restore: the
#    mirror set and state schema are unchanged, so SERVICE_READ_VERSIONS
#    accepts both; a ServiceEngine restore ignores the block either way.
SERVICE_FORMAT_VERSION = 2
SERVICE_READ_VERSIONS = (1, 2)
_SERVICE_TOPO_KEYS = ("src", "dst", "rev", "out_deg", "rows", "delay",
                      "free_nodes", "free_edges", "member")


def _state_classes() -> dict:
    from flow_updating_tpu.models.sync import NodeSyncState

    return {
        "FlowUpdatingState": FlowUpdatingState,
        "NodeSyncState": NodeSyncState,
    }


def topology_fingerprint(topo) -> dict:
    """Cheap content digest binding a checkpoint to its graph."""
    h = hashlib.sha256()
    for arr in (topo.src, topo.dst, topo.delay, topo.values):
        a = np.ascontiguousarray(arr)
        h.update(a.tobytes())
    return {
        "num_nodes": int(topo.num_nodes),
        "num_edges": int(topo.num_edges),
        "digest": h.hexdigest(),
    }


#: Chaos-harness crash-point hook: called with the final path between
#: the temp write and its atomic rename (resilience/chaos.py plants a
#: SIGKILL here to prove mid-checkpoint-write kills recover cleanly).
_CRASH_BEFORE_REPLACE = None

_TMP_RE = re.compile(r"\.tmp\.\d+$")


def _write_archive(path: str, manifest: dict, arrays: dict) -> None:
    """Single durability-critical write path for every checkpoint flavor:
    compressed npz with the JSON manifest as a uint8 buffer, written to a
    pid-suffixed temp file and atomically renamed — a crash mid-write
    leaves a stale temp and NO final file, never a truncated archive at
    the final path.  A failed write removes its temp (only a hard kill
    can leave one; recovery sweeps and counts those)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f, __manifest__=np.frombuffer(
                    json.dumps(manifest).encode(), dtype=np.uint8
                ), **arrays,
            )
            f.flush()
            os.fsync(f.fileno())
        if _CRASH_BEFORE_REPLACE is not None:
            _CRASH_BEFORE_REPLACE(path)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def _open_archive(path: str):
    """Open a checkpoint archive with failures translated into errors
    that name the FILE and the likely fix — a truncated copy, a partial
    download, or a non-checkpoint file must never surface as a raw
    zipfile/pickle traceback."""
    import zipfile

    try:
        return np.load(path)
    except FileNotFoundError:
        raise ValueError(f"checkpoint {path}: no such file") from None
    except (zipfile.BadZipFile, EOFError, OSError, ValueError) as exc:
        if _TMP_RE.search(path):
            raise ValueError(
                f"checkpoint {path}: this is a partially-written temp "
                "file from an interrupted save (checkpoints write to "
                "a .tmp.<pid> then atomically rename) — restore from "
                "the final checkpoint path; the temp is garbage") from exc
        raise ValueError(
            f"checkpoint {path}: not a readable checkpoint archive "
            f"({type(exc).__name__}: {exc}) — the file is truncated, "
            "still being written, or not a checkpoint at all") from exc


def _read_manifest(z, path: str) -> dict:
    if "__manifest__" not in z.files:
        raise ValueError(
            f"checkpoint {path}: no manifest record — the archive is "
            "not a flow_updating_tpu checkpoint (or was truncated "
            "mid-write; checkpoints are written atomically, so re-save)")
    try:
        manifest = json.loads(bytes(z["__manifest__"]).decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise ValueError(
            f"checkpoint {path}: manifest is corrupt "
            f"({type(exc).__name__}: {exc})") from exc
    got = manifest.get("format_version")
    if got != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {path}: format version {got}, but this runtime "
            f"reads version {FORMAT_VERSION} — re-create the checkpoint "
            "with the current code (format 1 predates the depth-Q "
            "mailbox arrays and cannot be migrated)")
    return manifest


def save_checkpoint(
    path: str,
    state: FlowUpdatingState,
    cfg: RoundConfig,
    topo=None,
    extra: dict | None = None,
) -> None:
    """Write one atomic checkpoint file (``.npz``) at ``path``.

    If the topology has a computed edge coloring cached (the fast-pairwise
    prerequisite — minutes-scale on degree-skewed graphs at 100k+ nodes,
    see Topology.edge_coloring), it rides along and is re-seeded on
    restore, so a resumed run never recolors.
    """
    arrays = {}
    for name in state.__dataclass_fields__:
        leaf = getattr(state, name)
        arrays[f"state.{name}"] = np.asarray(jax.device_get(leaf))
    coloring = getattr(topo, "_edge_coloring", None) if topo is not None \
        else None
    if coloring is not None:
        arrays["aux.edge_color"] = coloring[0]
    manifest = {
        "format_version": FORMAT_VERSION,
        "state_class": type(state).__name__,
        "config": dataclasses.asdict(cfg),
        "topology": topology_fingerprint(topo) if topo is not None else None,
        "dtypes": {k[len("state."):]: str(v.dtype)
                   for k, v in arrays.items() if k.startswith("state.")},
        "num_colors": coloring[1] if coloring is not None else None,
        "extra": extra or {},
    }
    _write_archive(path, manifest, arrays)


def load_checkpoint(
    path: str,
    topo=None,
) -> tuple[FlowUpdatingState, RoundConfig, dict]:
    """Read a checkpoint.  Returns ``(state, config, extra)``.

    If ``topo`` is given and the checkpoint carries a fingerprint, they must
    match — a checkpoint can never be resumed against a different graph.
    """
    with _open_archive(path) as z:
        manifest = _read_manifest(z, path)
        fields = {}
        aux_color = None
        for key in z.files:
            if key.startswith("state."):
                fields[key[len("state."):]] = z[key]
            elif key == "aux.edge_color":
                aux_color = z[key]
    cls_name = manifest.get("state_class", "FlowUpdatingState")
    classes = _state_classes()
    if cls_name not in classes:
        raise ValueError(f"unknown checkpoint state class {cls_name!r}")
    state_cls = classes[cls_name]
    want = set(state_cls.__dataclass_fields__)
    have = set(fields)
    if have != want:
        raise ValueError(
            f"checkpoint fields mismatch: missing {sorted(want - have)}, "
            f"unexpected {sorted(have - want)}"
        )
    if topo is not None and manifest.get("topology"):
        fp = topology_fingerprint(topo)
        if fp != manifest["topology"]:
            raise ValueError(
                "checkpoint was taken on a different topology "
                f"(saved {manifest['topology']['num_nodes']} nodes/"
                f"{manifest['topology']['num_edges']} edges, have "
                f"{fp['num_nodes']}/{fp['num_edges']}, digests "
                f"{'match' if fp['digest'] == manifest['topology']['digest'] else 'differ'})"
            )
        # re-seed the cached edge coloring (fingerprint-validated, so it
        # is guaranteed to describe this exact edge list)
        if aux_color is not None and manifest.get("num_colors") is not None:
            object.__setattr__(
                topo, "_edge_coloring",
                (aux_color, int(manifest["num_colors"])),
            )
    cfg = RoundConfig(**manifest["config"])

    # Dtype validation: a checkpoint saved under x64 (float64/int64 leaves)
    # restored in an x64-disabled runtime would be *silently* downcast to
    # 32-bit the moment the numpy leaves enter jit, quietly changing
    # trajectories while claiming a bit-exact resume.  Detect that here and
    # make the cast loud and explicit instead.
    saved_dtypes = manifest.get("dtypes", {})
    for name, arr in fields.items():
        saved = saved_dtypes.get(name)
        if saved is not None and str(arr.dtype) != saved:
            raise ValueError(
                f"checkpoint leaf {name!r} dtype {arr.dtype} does not match "
                f"its manifest entry {saved!r} (corrupt archive?)"
            )
        canonical = jax.dtypes.canonicalize_dtype(arr.dtype)
        if canonical != arr.dtype:
            warnings.warn(
                f"checkpoint leaf {name!r} was saved as {arr.dtype} but this "
                f"runtime canonicalizes it to {canonical} (jax_enable_x64 is "
                "off) — casting explicitly; the resume is NOT bit-exact",
                stacklevel=2,
            )
            fields[name] = arr.astype(canonical)

    state = state_cls(**fields)
    return state, cfg, manifest.get("extra", {})


# ---- VectorActor carries (user-defined pytrees) -------------------------
#
# A custom actor's state is an arbitrary pytree, so the archive keys are
# the jax keystr paths of its leaves, and restore is TEMPLATE-based: the
# caller passes a freshly-initialized carry from the SAME actor code, and
# every template leaf is filled from the archive (exact key-set, shape
# and dtype match required).  This binds a checkpoint to the actor's
# current structure the same way the fingerprint binds it to the graph —
# a protocol change between save and restore fails loudly instead of
# unflattening garbage.

def save_actor_checkpoint(path, carry, actor_name: str, topo=None,
                          extra: dict | None = None) -> None:
    import jax.tree_util as jtu

    leaves = jtu.tree_leaves_with_path(carry)
    arrays = {}
    for kp, v in leaves:
        arrays[f"leaf{jtu.keystr(kp)}"] = np.asarray(jax.device_get(v))
    manifest = {
        "format_version": FORMAT_VERSION,
        "state_class": "ActorCarry",
        "actor": actor_name,
        "topology": topology_fingerprint(topo) if topo is not None else None,
        "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
        "extra": extra or {},
    }
    _write_archive(path, manifest, arrays)


def load_actor_checkpoint(path, template, actor_name: str, topo=None):
    """Restore a carry saved by :func:`save_actor_checkpoint`.

    ``template``: a freshly-initialized carry from the same actor on the
    same topology — its structure defines what the archive must contain.
    Returns ``(carry, extra)``; leaves keep the template's device
    placement (sharded templates re-place restored leaves).
    """
    import jax.tree_util as jtu

    with _open_archive(path) as z:
        manifest = _read_manifest(z, path)
        if manifest.get("state_class") != "ActorCarry":
            raise ValueError(
                f"not a VectorActor checkpoint "
                f"(state_class={manifest.get('state_class')!r})")
        if manifest.get("actor") != actor_name:
            raise ValueError(
                f"checkpoint was saved by actor {manifest.get('actor')!r}, "
                f"restoring under {actor_name!r}")
        saved = {k: z[k] for k in z.files if k.startswith("leaf")}
    if topo is not None and manifest.get("topology"):
        fp = topology_fingerprint(topo)
        if fp != manifest["topology"]:
            raise ValueError(
                "actor checkpoint was taken on a different topology")

    paths, treedef = jtu.tree_flatten_with_path(template)
    want = {f"leaf{jtu.keystr(kp)}" for kp, _ in paths}
    if want != set(saved):
        raise ValueError(
            "actor checkpoint structure does not match the current "
            f"actor's init: missing {sorted(want - set(saved))}, "
            f"unexpected {sorted(set(saved) - want)} (the protocol "
            "changed since the save?)")
    saved_dtypes = manifest.get("dtypes", {})
    leaves = []
    for kp, tleaf in paths:
        key = f"leaf{jtu.keystr(kp)}"
        arr = saved[key]
        # shape/dtype from metadata only — never np.asarray(tleaf): that
        # would gather a sharded template to host (and raise outright on
        # non-fully-addressable multi-process arrays)
        tshape = np.shape(tleaf)
        tdtype = np.dtype(getattr(tleaf, "dtype", np.asarray(tleaf).dtype))
        if arr.shape != tshape:
            raise ValueError(
                f"actor checkpoint leaf {jtu.keystr(kp)} has shape "
                f"{arr.shape}, current actor expects {tshape}")
        man_dtype = saved_dtypes.get(key)
        if man_dtype is not None and str(arr.dtype) != man_dtype:
            raise ValueError(
                f"actor checkpoint leaf {jtu.keystr(kp)} dtype "
                f"{arr.dtype} does not match its manifest entry "
                f"{man_dtype!r} (corrupt archive?)")
        canonical = jax.dtypes.canonicalize_dtype(arr.dtype)
        if canonical != arr.dtype:
            warnings.warn(
                f"actor leaf {jtu.keystr(kp)} saved as {arr.dtype}, "
                f"canonicalized to {canonical} — resume is NOT bit-exact",
                stacklevel=2)
            arr = arr.astype(canonical)
        if np.dtype(canonical) != tdtype:
            raise ValueError(
                f"actor checkpoint leaf {jtu.keystr(kp)} restores as "
                f"{canonical}, but the current actor's init produces "
                f"{tdtype} — the protocol's precision changed since "
                "the save")
        dev = jax.numpy.asarray(arr)
        sh = getattr(tleaf, "sharding", None)
        if sh is not None:
            dev = jax.device_put(dev, sh)
        leaves.append(dev)
    return jtu.tree_unflatten(treedef, leaves), manifest.get("extra", {})


# ---- service checkpoints (ServiceEngine) --------------------------------
#
# A service checkpoint is a run checkpoint PLUS the dynamic topology the
# membership events have produced: the live src/dst/rev/out_deg/row-
# matrix/delay mirrors, the free-slot lists and the member mask.  There
# is no topology fingerprint — the graph is mutable state, not an input
# — so the whole mirror set is archived and the schema is versioned
# separately (SERVICE_FORMAT_VERSION) on top of the archive format.

def save_service_checkpoint(path: str, state: FlowUpdatingState,
                            cfg: RoundConfig, topo_arrays: dict,
                            meta: dict) -> None:
    """Write one atomic service checkpoint (state + dynamic topology +
    capacity metadata).  ``topo_arrays`` must carry exactly the
    :data:`_SERVICE_TOPO_KEYS` mirrors; ``meta`` is the JSON capacity /
    epoch block echoed back by :func:`load_service_checkpoint`."""
    missing = set(_SERVICE_TOPO_KEYS) - set(topo_arrays)
    if missing:
        raise ValueError(
            f"service checkpoint needs topology mirrors {sorted(missing)}")
    arrays = {}
    for name in state.__dataclass_fields__:
        arrays[f"state.{name}"] = np.asarray(
            jax.device_get(getattr(state, name)))
    for key in _SERVICE_TOPO_KEYS:
        arrays[f"svc.{key}"] = np.asarray(topo_arrays[key])
    manifest = {
        "format_version": FORMAT_VERSION,
        "state_class": type(state).__name__,
        "service_version": SERVICE_FORMAT_VERSION,
        "config": dataclasses.asdict(cfg),
        "dtypes": {k: str(v.dtype) for k, v in arrays.items()},
        "service": dict(meta),
        "extra": {},
    }
    _write_archive(path, manifest, arrays)


def load_service_checkpoint(path: str):
    """Read a service checkpoint.  Returns
    ``(state, config, topo_arrays, meta)``; raises a ValueError naming
    the file and the expected schema on a non-service archive, a version
    mismatch, or a truncated/incomplete mirror set."""
    with _open_archive(path) as z:
        manifest = _read_manifest(z, path)
        if "service_version" not in manifest:
            raise ValueError(
                f"checkpoint {path}: not a service checkpoint "
                f"(state_class={manifest.get('state_class')!r}, no "
                "service_version) — service archives are written by "
                "ServiceEngine.save_checkpoint; plain run checkpoints "
                "restore via Engine.restore_checkpoint")
        got = manifest["service_version"]
        if got not in SERVICE_READ_VERSIONS:
            readable = "/".join(str(v) for v in SERVICE_READ_VERSIONS)
            raise ValueError(
                f"checkpoint {path}: service schema version {got}, but "
                f"this runtime reads versions {readable} (writes "
                f"{SERVICE_FORMAT_VERSION}) — re-create the checkpoint "
                "with the current code")
        try:
            fields = {k[len("state."):]: z[k] for k in z.files
                      if k.startswith("state.")}
            svc = {k[len("svc."):]: z[k] for k in z.files
                   if k.startswith("svc.")}
        except Exception as exc:
            # member reads are lazy: in-place corruption (a bitflipped
            # byte, a torn copy) surfaces HERE as zlib/zipfile errors,
            # not at open — translate so ring fallback and callers see
            # one exception type naming the file and the fix
            raise ValueError(
                f"checkpoint {path}: archive member unreadable "
                f"({type(exc).__name__}: {exc}) — the file is corrupt "
                "(bitflip or torn copy); restore from an older "
                "checkpoint") from exc
    want = set(FlowUpdatingState.__dataclass_fields__)
    have = set(fields)
    if have != want:
        raise ValueError(
            f"checkpoint {path}: state fields mismatch — missing "
            f"{sorted(want - have)}, unexpected {sorted(have - want)} "
            "(truncated archive, or saved by an incompatible version)")
    missing = set(_SERVICE_TOPO_KEYS) - set(svc)
    if missing:
        raise ValueError(
            f"checkpoint {path}: service topology mirrors missing "
            f"{sorted(missing)} (truncated archive?)")
    saved_dtypes = manifest.get("dtypes", {})
    for name, arr in fields.items():
        saved = saved_dtypes.get(f"state.{name}")
        if saved is not None and str(arr.dtype) != saved:
            raise ValueError(
                f"checkpoint {path}: leaf {name!r} dtype {arr.dtype} "
                f"does not match its manifest entry {saved!r} (corrupt "
                "archive?)")
        canonical = jax.dtypes.canonicalize_dtype(arr.dtype)
        if canonical != arr.dtype:
            warnings.warn(
                f"service checkpoint leaf {name!r} was saved as "
                f"{arr.dtype} but this runtime canonicalizes it to "
                f"{canonical} — casting explicitly; the resume is NOT "
                "bit-exact", stacklevel=2)
            fields[name] = arr.astype(canonical)
    cfg = RoundConfig(**manifest["config"])
    state = FlowUpdatingState(**fields)
    return state, cfg, svc, manifest.get("service", {})
