"""Profiling helpers.

The reference's tracing story is SimGrid's (unused) Paje-trace CLI flags
(SURVEY.md §5); on TPU the native equivalent is the JAX/XLA profiler: a
trace context that captures device timelines, fusion boundaries and HBM
traffic, viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str | None):
    """``with trace('/tmp/fu-trace'):`` — profile the enclosed device work.

    ``log_dir=None`` is a no-op, so call sites can thread a CLI flag through
    unconditionally.
    """
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named sub-span inside a trace (shows up on the TensorBoard timeline)."""
    return jax.profiler.TraceAnnotation(name)
