"""Pallas TPU halo exchange: async remote DMA hidden behind interior work.

The halo kernel's cut-edge exchange (:mod:`flow_updating_tpu.parallel.
sharded`) ships each shard's boundary payload block to one neighbor per
plan-time offset.  As ``lax.ppermute`` ops those collectives serialize
against the round compute unless XLA's latency-hiding scheduler splits
them; this module is the TPU-native alternative — the SNIPPETS.md
[1]/[2] right-permute recipe: a ``pl.pallas_call`` running *inside*
``shard_map`` that

1. **starts** one ``pltpu.make_async_remote_copy`` per shard offset
   (send/recv DMA semaphores in scratch, logical device ids from
   ``lax.axis_index``),
2. **merges while the DMA is in flight** — the intra-shard delivery
   merge, i.e. every ring-buffer write that does not touch a cut edge,
   expressed in the receiver-pull (gather) form so it is a dense
   elementwise select over the ``(D, Eb)`` buffers, and
3. **waits** on the receive semaphores, handing the received frontier
   blocks back for the caller to scatter into the cut edges' slots.

The merge is the only work that can sit in the DMA window: a
``pallas_call`` is a synchronous custom call whose scratch semaphores
die with the kernel, so ``start()`` and ``wait()`` must share one
invocation, and the merge operands are the round's fire outputs — the
kernel therefore launches *after* the interior deliver/fire pass and
hides the wire behind the O(D*Eb) merge, not the whole interior.  The
full-interior window is ``halo='overlap'``'s (XLA async ppermutes);
widening this kernel's window means moving deliver/fire into Pallas.

Semantics are exactly ``lax.ppermute(payload, [(s, (s+d) % S)])`` per
offset plus the unfused buffer merge — pinned bit-for-bit by
``tests/test_overlap.py`` in Pallas **interpret mode** on the virtual
CPU mesh (interpret mode executes the real remote-copy semantics, so
the shipped kernel is the tested kernel).  Off-TPU callers default to
interpret mode; the production CPU/GPU path is the ``halo='overlap'``
ppermute schedule in :mod:`flow_updating_tpu.parallel.overlap`, which
XLA's async collectives overlap natively.
"""

from __future__ import annotations

import functools

import numpy as np

#: the Mosaic compiler-params class, resolved ONCE at import under the
#: names it has carried across jax releases (``TPUCompilerParams`` up to
#: ~0.4.x, ``CompilerParams`` afterwards).  Cross-chip DMA kernels need
#: its ``collective_id`` on real hardware; interpret mode never touches
#: it.  ``None`` here means THIS jax exposes neither name — resolved
#: eagerly so the failure is a named error at first hardware use
#: (:func:`require_compiler_params`), not a silently dropped parameter.
_COMPILER_PARAMS_NAMES = ("TPUCompilerParams", "CompilerParams")


def _resolve_compiler_params_cls():
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:
        # a jax build whose Mosaic extras fail to import can still use
        # every interpret-mode path in this module; the None sentinel
        # surfaces as require_compiler_params' named error at first
        # hardware use
        return None
    for name in _COMPILER_PARAMS_NAMES:
        cls = getattr(pltpu, name, None)
        if cls is not None:
            return cls
    return None


_COMPILER_PARAMS_CLS = _resolve_compiler_params_cls()


def require_compiler_params(collective_id: int):
    """The ``compiler_params`` value for a cross-chip DMA
    ``pallas_call`` on real TPU hardware.  Raises a named error (jax
    version + the class names probed) when this jax exposes no Mosaic
    params class — a silent omission would deadlock collective kernels
    on device instead."""
    if _COMPILER_PARAMS_CLS is None:
        import jax

        raise RuntimeError(
            "cannot compile a cross-chip DMA kernel: jax "
            f"{jax.__version__} exposes none of "
            f"{'/'.join('pallas.tpu.' + n for n in _COMPILER_PARAMS_NAMES)}"
            " — the Mosaic compiler-params class moved again; add its "
            "current name to ops/pallas_halo._COMPILER_PARAMS_NAMES")
    return _COMPILER_PARAMS_CLS(collective_id=collective_id)


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def _exchange_kernel(*refs, offsets, axis_name, axis_size, n_extra):
    """Kernel body: start every offset's remote copy, run the interior
    merge while the wire is busy, wait.  ``refs`` lays out as::

        [pay_0 .. pay_{k-1},  extra_in...,        # inputs
         recv_0 .. recv_{k-1}, extra_out...,      # outputs
         send_sem_0, recv_sem_0, ...]             # scratch DMA semaphores

    with ``extra`` the interior-merge operands (hit mask, payload
    planes, ring buffers) when fused, empty for a pure exchange."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    k = len(offsets)
    n_out_extra = 3 if n_extra else 0
    pay = refs[:k]
    extra_in = refs[k:k + n_extra]
    recv = refs[k + n_extra:2 * k + n_extra]
    extra_out = refs[2 * k + n_extra:2 * k + n_extra + n_out_extra]
    sems = refs[2 * k + n_extra + n_out_extra:]

    me = jax.lax.axis_index(axis_name)
    ops = []
    for i, d in enumerate(offsets):
        nbr = jax.lax.rem(me + np.int32(d), np.int32(axis_size))
        op = pltpu.make_async_remote_copy(
            src_ref=pay[i],
            dst_ref=recv[i],
            send_sem=sems[2 * i],
            recv_sem=sems[2 * i + 1],
            device_id=nbr,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        ops.append(op)

    if n_extra:
        # interior merge while the DMAs are in flight: receiver-pull
        # delivery of intra-shard messages — hit[d, e] selects the
        # sender's payload plane into the ring-buffer cell, elementwise
        hit = extra_in[0][...]
        pflow = extra_in[1][...]
        pest = extra_in[2][...]
        bflow = extra_in[3][...]
        best_ = extra_in[4][...]
        bvalid = extra_in[5][...]
        hx = hit
        while hx.ndim < bflow.ndim:
            hx = hx[..., None]
        extra_out[0][...] = jnp.where(hx, pflow[None], bflow)
        extra_out[1][...] = jnp.where(hx, pest[None], best_)
        extra_out[2][...] = bvalid | hit

    for op in ops:
        op.wait()


def remote_block_exchange(payloads, offsets, *, axis_name, axis_size,
                          interpret=None):
    """Exchange one ``(L, H_d)`` payload block per shard offset.

    ``payloads[i]`` is this shard's block for offset ``offsets[i]``;
    returns the blocks received from shards ``(me - d) % S`` — exactly
    ``[lax.ppermute(p, axis, [(s, (s+d) % S) for s in range(S)]) ...]``,
    but through one Pallas kernel whose remote DMAs all start before any
    completes.  With no merge workload there is nothing between
    ``start()`` and ``wait()`` — the exchange itself is serialized (the
    fast-pairwise caller's case); the overlap window belongs to
    :func:`fused_exchange_merge`.  ``interpret=None`` auto-selects
    interpret mode off-TPU.
    """
    return _call(payloads, offsets, extra=None, axis_name=axis_name,
                 axis_size=axis_size, interpret=interpret)


def fused_exchange_merge(payloads, offsets, hit, pay_flow, pay_est,
                         buf_flow, buf_est, buf_valid, *, axis_name,
                         axis_size, interpret=None):
    """The fused overlap step: start every boundary DMA, merge the
    intra-shard deliveries into the ring buffers while the wire is
    busy, wait.  Returns ``(received_blocks, buf_flow, buf_est,
    buf_valid)``; the merge is the receiver-pull form ``buf[d, e] =
    hit[d, e] ? payload[e] : buf[d, e]`` — bit-identical to the
    unfused scatter (targets are unique, writes are pure replacement).
    """
    extra = (hit, pay_flow, pay_est, buf_flow, buf_est, buf_valid)
    out = _call(payloads, offsets, extra=extra, axis_name=axis_name,
                axis_size=axis_size, interpret=interpret)
    k = len(offsets)
    return list(out[:k]), out[k], out[k + 1], out[k + 2]


def _call(payloads, offsets, *, extra, axis_name, axis_size, interpret):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = not _on_tpu()
    offsets = tuple(int(d) for d in offsets)
    payloads = list(payloads)
    if not payloads:
        if extra is None:
            return []  # no cut edges anywhere: nothing on the wire
        raise ValueError("fused merge needs at least one offset block")
    n_extra = 0 if extra is None else len(extra)
    inputs = payloads + (list(extra) if extra else [])
    out_shape = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in payloads]
    if extra:
        out_shape += [jax.ShapeDtypeStruct(extra[i].shape, extra[i].dtype)
                      for i in (3, 4, 5)]
    spec = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
    kwargs = {}
    if not interpret:
        # cross-chip DMA kernels need a collective id on real hardware;
        # the params class is import-resolved and REQUIRED here — a
        # missing class fails with the jax version named rather than
        # compiling a kernel that deadlocks on device
        kwargs["compiler_params"] = require_compiler_params(
            collective_id=0)
    out = pl.pallas_call(
        functools.partial(_exchange_kernel, offsets=offsets,
                          axis_name=axis_name, axis_size=int(axis_size),
                          n_extra=n_extra),
        out_shape=tuple(out_shape),
        in_specs=[spec] * len(inputs),
        out_specs=tuple([spec] * len(out_shape)),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * (2 * len(offsets)),
        interpret=interpret,
        **kwargs,
    )(*inputs)
    return list(out) if isinstance(out, (tuple, list)) else [out]
