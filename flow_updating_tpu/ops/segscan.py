"""Segmented affine scan — sequential-within-tick semantics, in parallel.

The reference's pairwise variant mutates a node's running estimate *between*
consecutive ``avg_and_send`` calls in one tick (``flowupdating-pairwise.py:
86-91`` fires every stale neighbor in a Python for-loop; each call reads
``value - sum(flows)`` after the previous call's flow update).  Each firing
edge therefore applies an affine map to the node's running estimate:

    x -> (x + est_e) / 2          (firing edge)
    x -> x                        (non-firing edge)

Sequential per node, but nodes' out-edges are contiguous segments of the
edge axis — so the whole thing is one segmented inclusive scan of affine-map
compositions via ``jax.lax.associative_scan``.  This keeps the reference's
exact sequential dynamics while staying a single fused vector op on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segmented_affine_scan(a, b, seg_start):
    """Inclusive scan of affine-map composition within segments.

    Element i carries the map ``x -> a[i] * x + b[i]``; ``seg_start[i]`` is
    True where a new segment begins.  Returns ``(A, B)`` such that the
    composition of maps ``seg_first..i`` is ``x -> A[i] * x + B[i]``.

    ``b`` may carry trailing feature axes (vector payloads: the same
    scale ``a`` applies one affine map per feature); ``a`` and
    ``seg_start`` stay 1-D over the scanned axis.
    """
    seg_start = seg_start.astype(bool)
    ext = b.ndim - a.ndim
    up = (lambda m: m.reshape(m.shape + (1,) * ext)) if ext else (lambda m: m)

    def combine(left, right):
        a1, b1, f1 = left
        a2, b2, f2 = right
        # right-after-left: x -> a2*(a1 x + b1) + b2, unless right starts a
        # new segment, in which case left is discarded.
        a_out = jnp.where(f2, a2, a2 * a1)
        b_out = jnp.where(up(f2), b2, up(a2) * b1 + b2)
        return a_out, b_out, f1 | f2

    A, B, _ = jax.lax.associative_scan(combine, (a, b, seg_start))
    return A, B
