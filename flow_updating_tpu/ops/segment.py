"""Segment reductions over the edge axis.

The "mailbox" of the reference (SimGrid rendezvous matching, SURVEY.md N4)
degenerates on TPU to segment reductions over the sorted ``src`` index
vector: summing a node's incoming flow ledger, checking whether all
neighbors have reported, picking which pending message a node drains this
round.  Edges are sorted by ``src`` at topology build time so every wrapper
passes ``indices_are_sorted=True``.

**Batching rule.**  ``jax.vmap`` of a segment reduction lowers to a
*batched* scatter, which XLA:CPU executes as a serialized per-element
update loop — measured ~100x slower than one lane run B times, which
would sink the sweep engine's whole premise.  Each wrapper therefore
carries a ``jax.custom_batching.custom_vmap`` rule that flattens the
batch instead: lane ``b``'s segment ids are offset by ``b *
num_segments`` and the reduction runs ONCE over the flattened ``(B*E,)``
axis with ``B*num_segments`` segments.  Lane-major offsets keep the ids
globally sorted (each lane's ids are sorted by construction), so the
flattened form takes the same fast sorted-segment lowering as the
single-instance path — bit-identical results, one scatter for the whole
bucket.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _flat_segment_rule(op):
    """custom_vmap rule factory: run ``op`` once over the lane-flattened
    axis with per-lane segment-id offsets (see module docstring)."""

    def rule(num_segments, axis_size, in_batched, data, segment_ids):
        data_b, ids_b = in_batched
        B = axis_size
        if not ids_b:
            segment_ids = jnp.broadcast_to(
                segment_ids, (B,) + segment_ids.shape)
        if not data_b:
            data = jnp.broadcast_to(data, (B,) + data.shape)
        offs = jnp.arange(B, dtype=segment_ids.dtype) * num_segments
        flat_ids = (segment_ids + offs[:, None]).reshape(-1)
        flat = data.reshape((-1,) + data.shape[2:])
        out = op(flat, flat_ids, num_segments=B * num_segments,
                 indices_are_sorted=True)
        return out.reshape((B, num_segments) + out.shape[1:]), True

    return rule


@functools.lru_cache(maxsize=None)
def _segment_op(name: str, num_segments: int):
    """One custom_vmap-wrapped reduction per (op, num_segments) — the
    segment count must stay a Python int (static shape), so it is bound
    by closure rather than passed through the vmapped call."""
    op = getattr(jax.ops, f"segment_{name}")

    @jax.custom_batching.custom_vmap
    def f(data, segment_ids):
        return op(data, segment_ids, num_segments=num_segments,
                  indices_are_sorted=True)

    f.def_vmap(functools.partial(_flat_segment_rule(op), num_segments))
    return f


def segment_sum(data, segment_ids, num_segments: int):
    return _segment_op("sum", num_segments)(data, segment_ids)


def segment_max(data, segment_ids, num_segments: int):
    return _segment_op("max", num_segments)(data, segment_ids)


def segment_min(data, segment_ids, num_segments: int):
    return _segment_op("min", num_segments)(data, segment_ids)


def segment_all(pred, segment_ids, num_segments: int):
    """Per-segment logical AND of a boolean edge predicate.

    Empty segments (isolated nodes) return False.
    """
    mins = segment_min(pred.astype(jnp.int32), segment_ids, num_segments)
    counts = segment_sum(jnp.ones_like(pred, jnp.int32), segment_ids, num_segments)
    return (mins == 1) & (counts > 0)


# ---- scatter-free uniform-width row reductions (the sweep layout) --------
#
# The batched sweep cannot afford scatters at all (XLA:CPU executes them
# as serial per-element loops — the flat custom_vmap rule above bounds
# the damage but the loop remains).  Its packed topologies instead carry
# ONE dense (N, W) out-edge index matrix per lane (W = the bucket's max
# degree, pad slot = E), and reductions unroll the W columns
# *sequentially*: the accumulator starts at the op's initial value and
# folds edge values in CSR edge order — the exact addition order of the
# sorted scatter-add, so float sums stay BIT-IDENTICAL to the
# single-instance segment path while lowering to W gathers + W
# elementwise ops (vector-friendly, batches cleanly under vmap).


def _rows_fold(values, rows, init, combine):
    feat = values.shape[1:]
    xp = jnp.concatenate(
        [values, jnp.full((1,) + feat, init, dtype=values.dtype)])
    acc = jnp.full((rows.shape[0],) + feat, init, dtype=values.dtype)
    for w in range(rows.shape[1]):
        acc = combine(acc, xp[rows[:, w]])
    return acc


def rows_segment_sum(values, rows):
    return _rows_fold(values, rows, 0, jnp.add)


def rows_segment_min(values, rows, identity):
    return _rows_fold(values, rows, identity, jnp.minimum)


def rows_segment_max(values, rows, identity):
    return _rows_fold(values, rows, identity, jnp.maximum)


def rows_segment_all(pred, rows, out_deg):
    """AND over each row's valid slots; empty rows (isolated nodes and
    ghost-free pad rows) are False — matching :func:`segment_all`."""
    mins = rows_segment_min(pred.astype(jnp.int32), rows, 1)
    return (mins == 1) & (out_deg > 0)


# ---- scatter-free variants over the degree-bucketed out-edge ELL layout ---
#
# Each reduction gathers edge values by the per-bucket (rows, width) edge-
# index matrices (padded with E -> a neutral-element slot), reduces rows,
# concatenates buckets (ascending-degree node order) and unpermutes back to
# original node order with one (N,) gather.  No scatter ops at all — the
# TPU-friendly lowering of the same per-node reductions.


def _ell_reduce(values, pad_value, topo, reducer, out_dtype=None):
    """``values`` is ``(E,)`` or ``(E, D)`` (vector payloads) — the pad
    slot, gathers and the axis-1 row reduction all broadcast over the
    trailing feature axes unchanged."""
    feat = values.shape[1:]
    xp = jnp.concatenate(
        [values, jnp.full((1,) + feat, pad_value, dtype=values.dtype)]
    )
    parts = []
    for m in topo.ell_edge_mats:
        if m.shape[1] == 0:
            parts.append(jnp.full((m.shape[0],) + feat, pad_value, xp.dtype))
        else:
            parts.append(reducer(xp[m]))
    cat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    out = cat[topo.ell_inv_perm]
    return out.astype(out_dtype) if out_dtype is not None else out


def ell_segment_sum(values, topo):
    return _ell_reduce(values, 0, topo, lambda v: jnp.sum(v, axis=1))


def ell_segment_min(values, topo, identity):
    return _ell_reduce(values, identity, topo, lambda v: jnp.min(v, axis=1))


def ell_segment_max(values, topo, identity):
    return _ell_reduce(values, identity, topo, lambda v: jnp.max(v, axis=1))


def ell_segment_all(pred, topo):
    """AND over each node's out-edges; empty rows (isolated nodes) False —
    matching :func:`segment_all`."""
    allr = _ell_reduce(
        pred.astype(jnp.int32), 1, topo, lambda v: jnp.min(v, axis=1)
    )
    return (allr == 1) & (topo.out_deg > 0)
