"""Segment reductions over the edge axis.

The "mailbox" of the reference (SimGrid rendezvous matching, SURVEY.md N4)
degenerates on TPU to segment reductions over the sorted ``src`` index
vector: summing a node's incoming flow ledger, checking whether all
neighbors have reported, picking which pending message a node drains this
round.  Edges are sorted by ``src`` at topology build time so every wrapper
passes ``indices_are_sorted=True``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_sum(data, segment_ids, num_segments: int):
    return jax.ops.segment_sum(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=True,
    )


def segment_max(data, segment_ids, num_segments: int):
    return jax.ops.segment_max(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=True,
    )


def segment_min(data, segment_ids, num_segments: int):
    return jax.ops.segment_min(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=True,
    )


def segment_all(pred, segment_ids, num_segments: int):
    """Per-segment logical AND of a boolean edge predicate.

    Empty segments (isolated nodes) return False.
    """
    mins = segment_min(pred.astype(jnp.int32), segment_ids, num_segments)
    counts = segment_sum(jnp.ones_like(pred, jnp.int32), segment_ids, num_segments)
    return (mins == 1) & (counts > 0)
