"""Segment reductions over the edge axis.

The "mailbox" of the reference (SimGrid rendezvous matching, SURVEY.md N4)
degenerates on TPU to segment reductions over the sorted ``src`` index
vector: summing a node's incoming flow ledger, checking whether all
neighbors have reported, picking which pending message a node drains this
round.  Edges are sorted by ``src`` at topology build time so every wrapper
passes ``indices_are_sorted=True``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def segment_sum(data, segment_ids, num_segments: int):
    return jax.ops.segment_sum(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=True,
    )


def segment_max(data, segment_ids, num_segments: int):
    return jax.ops.segment_max(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=True,
    )


def segment_min(data, segment_ids, num_segments: int):
    return jax.ops.segment_min(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=True,
    )


def segment_all(pred, segment_ids, num_segments: int):
    """Per-segment logical AND of a boolean edge predicate.

    Empty segments (isolated nodes) return False.
    """
    mins = segment_min(pred.astype(jnp.int32), segment_ids, num_segments)
    counts = segment_sum(jnp.ones_like(pred, jnp.int32), segment_ids, num_segments)
    return (mins == 1) & (counts > 0)


# ---- scatter-free variants over the degree-bucketed out-edge ELL layout ---
#
# Each reduction gathers edge values by the per-bucket (rows, width) edge-
# index matrices (padded with E -> a neutral-element slot), reduces rows,
# concatenates buckets (ascending-degree node order) and unpermutes back to
# original node order with one (N,) gather.  No scatter ops at all — the
# TPU-friendly lowering of the same per-node reductions.


def _ell_reduce(values, pad_value, topo, reducer, out_dtype=None):
    """``values`` is ``(E,)`` or ``(E, D)`` (vector payloads) — the pad
    slot, gathers and the axis-1 row reduction all broadcast over the
    trailing feature axes unchanged."""
    feat = values.shape[1:]
    xp = jnp.concatenate(
        [values, jnp.full((1,) + feat, pad_value, dtype=values.dtype)]
    )
    parts = []
    for m in topo.ell_edge_mats:
        if m.shape[1] == 0:
            parts.append(jnp.full((m.shape[0],) + feat, pad_value, xp.dtype))
        else:
            parts.append(reducer(xp[m]))
    cat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    out = cat[topo.ell_inv_perm]
    return out.astype(out_dtype) if out_dtype is not None else out


def ell_segment_sum(values, topo):
    return _ell_reduce(values, 0, topo, lambda v: jnp.sum(v, axis=1))


def ell_segment_min(values, topo, identity):
    return _ell_reduce(values, identity, topo, lambda v: jnp.min(v, axis=1))


def ell_segment_max(values, topo, identity):
    return _ell_reduce(values, identity, topo, lambda v: jnp.max(v, axis=1))


def ell_segment_all(pred, topo):
    """AND over each node's out-edges; empty rows (isolated nodes) False —
    matching :func:`segment_all`."""
    allr = _ell_reduce(
        pred.astype(jnp.int32), 1, topo, lambda v: jnp.min(v, axis=1)
    )
    return (allr == 1) & (topo.out_deg > 0)
