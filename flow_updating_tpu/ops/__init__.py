from flow_updating_tpu.ops.segment import (
    segment_sum,
    segment_min,
    segment_max,
    segment_all,
)
from flow_updating_tpu.ops.segscan import segmented_affine_scan
from flow_updating_tpu.ops.structured import (
    CompleteStruct,
    FatTreeStruct,
    Grid2dStruct,
    HypercubeStruct,
    RingStruct,
    Torus2dStruct,
    structured_neighbor_sum,
)

__all__ = [
    "segment_sum",
    "segment_min",
    "segment_max",
    "segment_all",
    "segmented_affine_scan",
    "CompleteStruct",
    "FatTreeStruct",
    "Grid2dStruct",
    "HypercubeStruct",
    "RingStruct",
    "Torus2dStruct",
    "structured_neighbor_sum",
]
