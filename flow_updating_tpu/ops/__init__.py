from flow_updating_tpu.ops.segment import (
    segment_sum,
    segment_min,
    segment_max,
    segment_all,
)
from flow_updating_tpu.ops.segscan import segmented_affine_scan

__all__ = [
    "segment_sum",
    "segment_min",
    "segment_max",
    "segment_all",
    "segmented_affine_scan",
]
