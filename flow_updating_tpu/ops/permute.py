"""Fixed permutations as Beneš switching networks — gather-free data movement.

Why: XLA lowers a dynamic gather ``x[idx]`` to a *scalar* loop on TPU
(~10 ns/element; measured to be ~92% of the node kernel's round time at
1M nodes — BENCH_NOTES.md).  But the framework's gathers are all *static*
maps fixed at topology-build time, and a fixed permutation needs no
gather hardware at all: route it through a Beneš network — ``2*log2(n)-1``
columns of 2x2 switches — whose swap decisions are precomputed on the
host.  Applying one column is ``where(mask, swap_within_pairs(x), x)``:
reshape + reverse + select, all dense VPU work at HBM bandwidth, no
scalar loop anywhere.  45 streamed passes beat 6M serialized gathers by
an order of magnitude.

This module provides the two host-side planners and the on-device
applicator:

* :func:`benes_plan` — route an arbitrary permutation (classic recursive
  cycle 2-coloring), returning per-stage swap masks.
* :func:`spread_plan` — route a *monotone injective* placement
  (``z[targets[i]] = x[i]``, targets strictly increasing) as a barrel
  shifter: log2(n) masked-roll stages, masks computed by exact host
  simulation.  Monotone routes are conflict-free, so no Beneš needed.
* :func:`apply_stages` — run the stages under jit (static masks).

The planners are numpy; :mod:`flow_updating_tpu.native` accelerates
Beneš routing in C++ at million-element scale (same output, asserted in
tests).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """Device-applicable stage sequence.

    ``kind`` per stage: 'swap' (Beneš column: exchange within pairs at
    ``dist``) or 'roll' (barrel-shifter stage: take the value ``dist``
    positions to the left).  Masks are bool (n,) host arrays, moved to
    device once by the consumer.
    """

    n: int
    dists: tuple
    kinds: tuple          # 'swap' | 'roll'
    masks: tuple          # (n,) bool per stage

    def device_masks(self):
        import jax.numpy as jnp

        return tuple(jnp.asarray(m) for m in self.masks)


def _route_block(p: np.ndarray) -> np.ndarray:
    """2-color the inputs of one Beneš recursion block.

    ``p`` is the block-local permutation (output o takes input ``p[o]``).
    Constraints: input pair (i, i^h) differ; sources of output pair
    (o, o^h) differ.  The constraint graph is a disjoint union of even
    cycles — walk each, alternating colors.
    """
    m = len(p)
    h = m // 2
    pinv = np.empty(m, np.int64)
    pinv[p] = np.arange(m, dtype=np.int64)
    color = np.full(m, -1, np.int8)
    for s in range(m):
        if color[s] != -1:
            continue
        i, c = s, 0
        while color[i] == -1:
            color[i] = c
            partner = i ^ h
            color[partner] = 1 - c
            i = int(p[pinv[partner] ^ h])
    return color


def benes_plan(perm: np.ndarray) -> StagePlan:
    """Swap-stage plan computing ``y = x[perm]`` for a power-of-two n.

    Uses the native C++ router when available (identical output);
    otherwise the numpy/python recursion below.
    """
    perm = np.asarray(perm, np.int64)
    n = len(perm)
    if n & (n - 1) or n < 2:
        raise ValueError("benes_plan needs power-of-two length >= 2")
    if np.any(np.sort(perm) != np.arange(n)):
        raise ValueError("not a permutation")
    k = n.bit_length() - 1

    from flow_updating_tpu import native

    masks_native = native.benes_route(perm) if n >= 1 << 14 else None
    if masks_native is not None:
        masks = masks_native
    else:
        masks = [np.zeros(n, bool) for _ in range(2 * k - 1)]
        perms = {0: perm}
        for level in range(k - 1):
            m = n >> level
            h = m >> 1
            nxt = {}
            for start, p in perms.items():
                color = _route_block(p)
                swap_in = color[:h] == 1
                masks[level][start: start + h] = swap_in
                masks[level][start + h: start + m] = swap_in
                pcol = color[p]
                swap_out = pcol[:h] == 1
                out_s = 2 * k - 2 - level
                masks[out_s][start: start + h] = swap_out
                masks[out_s][start + h: start + m] = swap_out
                up = np.where(pcol[:h] == 0, p[:h], p[h:m])
                lo = np.where(pcol[:h] == 0, p[h:m], p[:h])
                nxt[start] = up % h
                nxt[start + h] = lo % h
            perms = nxt
        for start, p in perms.items():   # middle column, size-2 blocks
            sw = p[0] == 1
            masks[k - 1][start] = sw
            masks[k - 1][start + 1] = sw
    dists = [n >> (level + 1) for level in range(k)]
    dists = dists + dists[-2::-1]
    return StagePlan(
        n=n, dists=tuple(dists), kinds=("swap",) * (2 * k - 1),
        masks=tuple(masks),
    )


def spread_plan(targets: np.ndarray, n: int) -> StagePlan:
    """Roll-stage plan placing ``x[i]`` at ``targets[i]`` (strictly
    increasing, ``targets[i] >= i``); other positions end up with
    unspecified junk.  Monotone non-crossing moves are realized bit by
    bit (largest shift first) — the host simulation tracks exact
    occupancy, so reads can never hit a vacated slot.
    """
    targets = np.asarray(targets, np.int64)
    if len(targets) and (np.any(np.diff(targets) <= 0)
                        or targets[-1] >= n
                        or np.any(targets < np.arange(len(targets)))):
        raise ValueError("targets must be strictly increasing, >= index, < n")
    offset = targets - np.arange(len(targets), dtype=np.int64)
    maxbit = int(offset.max()).bit_length() if len(targets) else 0
    # pos[i] = current position of element i; process bits high -> low
    pos = np.arange(len(targets), dtype=np.int64)
    dists, kinds, masks = [], [], []
    for k in range(maxbit - 1, -1, -1):
        d = 1 << k
        move = (offset & d) != 0
        mask = np.zeros(n, bool)
        mask[pos[move] + d] = True
        pos = pos + np.where(move, d, 0)
        dists.append(d)
        kinds.append("roll")
        masks.append(mask)
    return StagePlan(n=n, dists=tuple(dists), kinds=tuple(kinds),
                     masks=tuple(masks))


def fill_forward_stages(run_id: np.ndarray) -> StagePlan:
    """Roll-stage plan copying each run's HEAD value over the whole run.

    ``run_id`` (n,) is a non-decreasing array of run labels; position j's
    distance to its run head is static, so stage k copies from ``2^k`` to
    the left exactly where bit k of that distance is set (ascending bit
    order composes correctly within a run).
    """
    run_id = np.asarray(run_id)
    n = len(run_id)
    heads = np.zeros(n, bool)
    heads[0] = True
    heads[1:] = run_id[1:] != run_id[:-1]
    head_pos = np.maximum.accumulate(
        np.where(heads, np.arange(n, dtype=np.int64), -1)
    )
    dist = np.arange(n, dtype=np.int64) - head_pos
    maxbit = int(dist.max()).bit_length() if n else 0
    dists, kinds, masks = [], [], []
    for k in range(maxbit):
        d = 1 << k
        dists.append(d)
        kinds.append("roll")
        masks.append(((dist >> k) & 1).astype(bool))
    return StagePlan(n=n, dists=tuple(dists), kinds=tuple(kinds),
                     masks=tuple(masks))


@dataclasses.dataclass(frozen=True, eq=False)
class PaddedPermPlan:
    """A permutation on [0, n) routed through a power-of-two Beneš network
    (identity on the padding).  ``eq=False``: identity-hashed so it can be
    a jit-static field; masks travel separately as pytree leaves."""

    n: int
    stages: StagePlan

    def device_masks(self):
        return self.stages.device_masks()


@dataclasses.dataclass(frozen=True, eq=False)
class FusedPaddedPermPlan:
    """:class:`PaddedPermPlan` whose stages run as fused Pallas passes
    (``delivery='benes_fused'`` — see ops/pallas_fused.py)."""

    n: int
    stages: StagePlan
    fused: object        # pallas_fused.FusedPlan

    def device_masks(self):
        from flow_updating_tpu.ops.pallas_fused import device_mask_planes

        return device_mask_planes(self.stages, self.fused)


def next_pow2(x: int) -> int:
    """Smallest power of two >= x, floored at 2 (network minimum)."""
    return 1 << max(x - 1, 1).bit_length()


def padded_perm_plan(perm: np.ndarray, fused: bool = False):
    """Beneš plan for ``y = x[perm]`` with arbitrary (non-power-of-two)
    length; the network is padded to the next power of two.
    ``fused=True`` wraps the plan for the fused-Pallas executor when the
    network is large enough."""
    perm = np.asarray(perm, np.int64)
    n = len(perm)
    P = next_pow2(n)
    full = np.concatenate([perm, np.arange(n, P, dtype=np.int64)])
    stages = benes_plan(full)
    if fused:
        from flow_updating_tpu.ops.pallas_fused import MIN_P, plan_fused

        if P >= MIN_P:
            return FusedPaddedPermPlan(n=n, stages=stages,
                                       fused=plan_fused(stages))
    return PaddedPermPlan(n=n, stages=stages)


def apply_padded_perm(x, plan: PaddedPermPlan, masks_dev=None):
    """Apply over the last axis; pads to the network width and slices
    back."""
    import jax.numpy as jnp

    P = plan.stages.n
    pad = P - plan.n
    if pad:
        width = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        x = jnp.pad(x, width)
    if isinstance(plan, FusedPaddedPermPlan):
        from flow_updating_tpu.ops.pallas_fused import apply_fused

        if masks_dev is None:
            masks_dev = plan.device_masks()
        y = apply_fused(x, plan.fused, masks_dev)
    else:
        y = apply_stages(x, plan.stages, masks_dev)
    return y[..., : plan.n]


def concat_plans(*plans: StagePlan) -> StagePlan:
    n = plans[0].n
    assert all(p.n == n for p in plans)
    return StagePlan(
        n=n,
        dists=sum((p.dists for p in plans), ()),
        kinds=sum((p.kinds for p in plans), ()),
        masks=sum((p.masks for p in plans), ()),
    )


def apply_stages(x, plan: StagePlan, masks_dev=None):
    """Run the plan's stages on device, over the LAST axis of ``x`` (any
    leading batch dims share the masks — e.g. delivery moves three payload
    lanes through one network).  ``masks_dev`` lets the caller pass
    pre-uploaded mask arrays (tuple, same order)."""
    import jax.numpy as jnp

    import jax

    n = plan.n
    if masks_dev is None:
        masks_dev = plan.device_masks()
    for dist, kind, mask in zip(plan.dists, plan.kinds, masks_dev):
        if kind == "swap":
            if dist & (dist - 1):
                # the xor-butterfly below pairs p with p ^ dist; only a
                # power of two makes that the within-pairs exchange
                raise ValueError(
                    f"swap distance {dist} is not a power of two")
            # Swap within pairs at power-of-two ``dist`` is the butterfly
            # x[p] <- x[p ^ dist]; express it as two rolls + selects.
            # The direct form — reshape(..., -1, 2, dist) + flip — costs
            # ~300 us per 2M-element stage on TPU (5.2 ms at dist=1: the
            # sub-lane flip forces a scalar relayout) while a roll is a
            # pair of aligned slice-copies (~13-30 us); the iota fuses
            # into the selects for free.
            hi = (jax.lax.iota(jnp.int32, n) & dist) != 0
            x = jnp.where(
                mask & hi,
                jnp.roll(x, dist, axis=-1),
                jnp.where(mask & ~hi, jnp.roll(x, -dist, axis=-1), x),
            )
        else:  # roll: take the value `dist` to the left
            x = jnp.where(mask, jnp.roll(x, dist, axis=-1), x)
    return x
