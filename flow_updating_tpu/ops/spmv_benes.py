"""Gather-free neighbor sum: the adjacency SpMV as a permutation network.

Drop-in alternative to :func:`flow_updating_tpu.models.sync.neighbor_sum`
(``cfg.spmv='benes'``).  XLA lowers the ELL gather ``x[mat]`` to a scalar
loop on TPU (~10 ns/element — the whole round is gather-bound at 1M
nodes, BENCH_NOTES.md); here the same data movement runs as ~90 static
masked swap/roll stages, each a dense reshape/roll + select at HBM
bandwidth, no scalar loop anywhere.

Factorization (all maps are topology constants, planned on the host
once):

    x[idx_flat]  =  permute_benes( fill_forward( spread(x) ) )

* ``spread``: place ``x[v]`` at the first slot of value v's run in the
  *sorted* index list (monotone injective -> conflict-free barrel
  shifter, log2 P stages).  A synthetic leading block [0..m1) in the
  index list guarantees every value occurs, which both fixes the spread
  preconditions and makes the sorted runs cover all of x.
* ``fill_forward``: copy each run head over its run (static distance
  bits, log2 P stages).  After this, slot j of the sorted order holds
  ``x[sorted_idx[j]]``.
* ``permute_benes``: route sorted positions back to ELL slots (the
  inverse argsort — an arbitrary fixed permutation, 2 log2 P - 1 swap
  columns routed by the C++ planner).

The ELL row sums that follow are plain vectorized reductions.  Total
device work: ~(3 log2 P) streamed passes over a power-of-two padded
array — at 1M nodes/6M edges that is ~10 GB of HBM traffic versus ~60 ms
of serialized gather.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.ops.permute import (
    StagePlan,
    apply_stages,
    benes_plan,
    concat_plans,
    fill_forward_stages,
    next_pow2,
    spread_plan,
)


@dataclasses.dataclass(frozen=True, eq=False)
class NeighborSumPlan:
    """Host-side plan.  ``eq=False``: instances hash/compare by identity so
    the plan can ride through jit as a static (non-pytree) field; the mask
    arrays themselves travel separately as pytree leaves (embedding ~100
    multi-MB masks as jaxpr constants would wreck compile times)."""

    m1: int              # padded node-vector length incl. the zero slot
    P: int               # power-of-two network width
    flat_begin: int      # ELL payload offset inside the network domain
    bucket_shapes: tuple  # (rows, width) per ELL bucket
    stages: StagePlan

    def device_masks(self):
        return self.stages.device_masks()


@dataclasses.dataclass(frozen=True, eq=False)
class FusedNeighborSumPlan:
    """:class:`NeighborSumPlan` whose stages run as fused Pallas passes
    (``spmv='benes_fused'`` — see ops/pallas_fused.py).  Falls back to
    the plain plan when the network is too small for the (rows, 128)
    tiling."""

    base: NeighborSumPlan
    fused: object        # pallas_fused.FusedPlan

    @property
    def m1(self):
        return self.base.m1

    @property
    def P(self):
        return self.base.P

    @property
    def flat_begin(self):
        return self.base.flat_begin

    @property
    def bucket_shapes(self):
        return self.base.bucket_shapes

    def device_masks(self):
        from flow_updating_tpu.ops.pallas_fused import device_mask_planes

        return device_mask_planes(self.base.stages, self.fused)


_plan_cache: dict = {}

# Cross-process plan cache (VERDICT r3 item 4): routing the k=160 network
# costs ~55 s of host work per process, and a measurement session runs
# several processes against the same topology.  Routed base plans persist
# as packbits-compressed npz keyed by the ELL content hash; masks are
# bit-packed (8x) then zlib'd.  Set FU_PLAN_CACHE=0 to disable, or point
# it at a directory to relocate.  Failures only ever warn — the cache
# must never break planning.
import logging as _logging
import os as _os

_logger = _logging.getLogger("flow_updating_tpu.spmv_benes")


# Bump when plan_sections / spread_plan / fill_forward_stages /
# benes_plan routing logic changes: the content digest only covers the
# INPUT mats, so without this a stale cache would silently replay plans
# from before a routing fix.
_PLANNER_VERSION = 1
_DISK_FORMAT = 1


def _disk_cache_dir():
    env = _os.environ.get("FU_PLAN_CACHE", "")
    if env == "0":
        return None
    if env:
        return env
    # user cache dir, never the package tree: site-packages installs are
    # often read-only, and runtime data does not belong in the source tree
    xdg = _os.environ.get("XDG_CACHE_HOME",
                          _os.path.expanduser("~/.cache"))
    return _os.path.join(xdg, "flow_updating_tpu", "plans")


def _disk_path(key0):
    d = _disk_cache_dir()
    if d is None:
        return None
    m1, _shapes, digest = key0
    return _os.path.join(
        d, f"ns_v{_PLANNER_VERSION}_{digest[:20]}_m{m1}.npz")


def _disk_save(key0, plan: NeighborSumPlan) -> None:
    path = _disk_path(key0)
    if path is None:
        return
    try:
        _os.makedirs(_os.path.dirname(path), exist_ok=True)
        st = plan.stages
        arrays = {
            f"mask{i}": np.packbits(m) for i, m in enumerate(st.masks)
        }
        meta = dict(
            format=_DISK_FORMAT, m1=plan.m1, P=plan.P,
            flat_begin=plan.flat_begin,
            bucket_shapes=list(map(list, plan.bucket_shapes)),
            n=st.n, dists=list(st.dists), kinds=list(st.kinds),
        )
        import json as _json

        # trailing .npz makes savez write exactly this path (no suffix
        # guessing); unlink on failure so aborted writes cannot pile up
        tmp = path + f".{_os.getpid()}.tmp.npz"
        try:
            np.savez_compressed(tmp, meta=_json.dumps(meta), **arrays)
            _os.replace(tmp, path)
        except Exception:
            if _os.path.exists(tmp):
                _os.unlink(tmp)
            raise
    except Exception as exc:  # cache write is best-effort
        _logger.warning("plan disk-cache write failed (%s)", exc)


def _disk_load(key0):
    path = _disk_path(key0)
    if path is None or not _os.path.exists(path):
        return None
    try:
        import json as _json

        with np.load(path) as z:
            meta = _json.loads(str(z["meta"]))
            if meta.get("format") != _DISK_FORMAT:
                return None
            if tuple(tuple(s) for s in meta["bucket_shapes"]) != key0[1]:
                # the filename digest hashes raw bytes without per-matrix
                # delimiters — shape-distinct mats with identical bytes
                # would collide here; never trust a shape-mismatched hit
                return None
            masks = tuple(
                np.unpackbits(z[f"mask{i}"])[: meta["n"]].astype(bool)
                for i in range(len(meta["dists"]))
            )
        stages = StagePlan(
            n=meta["n"], dists=tuple(meta["dists"]),
            kinds=tuple(meta["kinds"]), masks=masks,
        )
        return NeighborSumPlan(
            m1=meta["m1"], P=meta["P"], flat_begin=meta["flat_begin"],
            bucket_shapes=tuple(tuple(s) for s in meta["bucket_shapes"]),
            stages=stages,
        )
    except Exception as exc:
        _logger.warning("plan disk-cache read failed (%s); replanning", exc)
        return None


def _mats_key(mats: tuple, m1: int):
    import hashlib

    h = hashlib.sha1()
    for m in mats:
        h.update(m.dtype.str.encode())
        h.update(np.ascontiguousarray(m))
    return (m1, tuple(m.shape for m in mats), h.hexdigest())


def plan_neighbor_sum(mats: tuple, m1: int, fused: bool = False):
    """Plan the network for the NodeKernel's ELL matrices.

    ``mats``: per-bucket (rows, width) int32 neighbor-slot matrices in
    padded node space, pad value ``m1 - 1`` (the zero slot).  ``m1`` =
    padded vector length + 1.  ``fused=True`` wraps the plan for the
    fused-Pallas executor when the network is large enough.

    The base plan is cached on the content of ``mats`` (sha1): routing
    the Benes network at 1M nodes costs tens of seconds, and the bench's
    ``--spmv auto`` mode plans the same topology for both benes
    variants.
    """
    key = (_mats_key(mats, m1), fused)
    cached = _plan_cache.get(key)
    if cached is not None:
        return cached
    base_cached = _plan_cache.get((key[0], False))
    if base_cached is not None and fused:
        # reuse the routed base; cache the wrapper too — the plans are
        # identity-hashed jit statics, so a fresh wrapper per call would
        # retrace the round program every time
        wrapped = _wrap_fused(base_cached)
        _plan_cache[key] = wrapped
        return wrapped
    plan = _disk_load(key[0])
    if plan is None:
        spread, fill, benes, P = plan_sections(mats, m1)
        plan = NeighborSumPlan(
            m1=m1, P=P, flat_begin=m1,
            bucket_shapes=tuple(m.shape for m in mats),
            stages=concat_plans(spread, fill, benes),
        )
        _disk_save(key[0], plan)
    _plan_cache[(key[0], False)] = plan
    out = plan
    if fused:
        out = _wrap_fused(plan)
        _plan_cache[key] = out
    while len(_plan_cache) > 8:   # bound held host memory (masks are big)
        _plan_cache.pop(next(iter(_plan_cache)))
    return out


def plan_sections(mats: tuple, m1: int, min_width: int = 0):
    """The three network sections (spread, fill, benes StagePlans) plus
    the common width ``P`` for one set of ELL matrices.  Exposed
    separately so the sharded planner can pad per-shard sections to a
    common stage skeleton before concatenation (``min_width`` floors P,
    e.g. at the fused executor's minimum)."""
    flats = [np.asarray(m, np.int64).ravel() for m in mats]
    idx_flat = (np.concatenate(flats) if flats
                else np.zeros(0, np.int64))
    # synthetic block: every value present at least once
    aug = np.concatenate([np.arange(m1, dtype=np.int64), idx_flat])
    Ea = len(aug)
    P = next_pow2(max(Ea, m1, min_width))

    order = np.argsort(aug, kind="stable")
    g = aug[order]
    heads = np.zeros(Ea, bool)
    heads[0] = True
    heads[1:] = g[1:] != g[:-1]
    head_pos = np.flatnonzero(heads)
    assert len(head_pos) == m1, "synthetic block guarantees all values"

    spread = spread_plan(head_pos, P)
    run_id = np.concatenate([g, np.full(P - Ea, g[-1] if Ea else 0)])
    fill = fill_forward_stages(run_id)
    # sorted position r holds x[g[r]]; ELL slot s needs x[aug[s]] =
    # value at sorted position inv_order[s]
    inv_order = np.empty(Ea, np.int64)
    inv_order[order] = np.arange(Ea, dtype=np.int64)
    perm2 = np.concatenate(
        [inv_order, np.arange(Ea, P, dtype=np.int64)]
    )
    benes = benes_plan(perm2)
    return spread, fill, benes, P


def pad_roll_section(plan: StagePlan, target_dists: tuple) -> StagePlan:
    """Extend a roll-stage section to a canonical dist list by inserting
    all-false-mask (no-op) stages; existing stages must appear in
    ``target_dists`` in order."""
    it = iter(zip(plan.dists, plan.masks))
    nxt = next(it, None)
    masks = []
    for d in target_dists:
        if nxt is not None and nxt[0] == d:
            masks.append(nxt[1])
            nxt = next(it, None)
        else:
            masks.append(np.zeros(plan.n, bool))
    if nxt is not None:
        raise ValueError("section dists not a subsequence of target")
    return StagePlan(n=plan.n, dists=tuple(target_dists),
                     kinds=("roll",) * len(target_dists),
                     masks=tuple(masks))


def _wrap_fused(plan: NeighborSumPlan):
    from flow_updating_tpu.ops.pallas_fused import MIN_P, plan_fused

    if plan.P >= MIN_P:
        return FusedNeighborSumPlan(base=plan,
                                    fused=plan_fused(plan.stages))
    return plan


def neighbor_sum_benes(x, plan: NeighborSumPlan, masks):
    """A(x) for the node kernel: x is the padded vector (m1 - 1,); the
    zero slot is appended here, exactly like the gather path's ``xp``.
    ``masks`` are the plan's stage masks as device arrays (pytree-carried
    by the caller)."""
    import jax.numpy as jnp

    # One flat pad: the zero slot (position m1-1) and the network padding
    # are both zeros, so a single concatenate covers both.  The obvious
    # nested form — concat the zero slot, then concat the pad — lowers to
    # a ~14x-slower program on TPU (measured 42.7 ms vs 3.8 ms per
    # application at P=262144): the unaligned intermediate forces a
    # lane-shift relayout of the whole network array.
    z = jnp.concatenate(
        [x, jnp.zeros((plan.P - plan.m1 + 1,), x.dtype)]
    )
    if isinstance(plan, FusedNeighborSumPlan):
        from flow_updating_tpu.ops.pallas_fused import apply_fused

        z = apply_fused(z, plan.fused, masks)
    else:
        z = apply_stages(z, plan.stages, masks)
    parts = []
    off = plan.flat_begin
    for rows, w in plan.bucket_shapes:
        if w == 0:
            parts.append(jnp.zeros((rows,), x.dtype))
        else:
            blk = z[off: off + rows * w].reshape(rows, w)
            parts.append(jnp.sum(blk, axis=1))
            off += rows * w
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]
