"""One-kernel banded round: fire → delivery → merge in a single Pallas pass.

The banded executor (``plan/banded.py`` driven by ``models/sync.py``)
lowers one Flow-Updating round as separate XLA ops — the fire decision
(an elementwise ``avg`` update), one masked-roll delivery per kept
diagonal, the remainder network, and the ledger merge — with an HBM
round trip between each.  At N nodes and L band lanes that is ``~3L+6``
streamed passes over the node vectors, the exact tax
``ops/pallas_fused.py`` already eliminated for permutation stages.  This
module executes the WHOLE round inside one ``pl.pallas_call``: a
band-tile of protocol state (S, G, avg_prev, A_prev plus the value /
degree constants) stays resident in VMEM while the kernel

1. **fires** — ``avg = (value - S + A_prev) / (deg + 1)``, computed on
   the halo-widened tile so every band read below finds its operand
   already on chip;
2. **delivers** — one ``where(mask_d, shift(avg, d), 0)`` accumulation
   per kept diagonal, masks bitpacked 32 lanes per ``uint32`` plane (the
   ``pallas_fused`` recipe), shifts as lane/sublane rolls of the VMEM
   window — no HBM between lanes;
3. **adds the remainder** — out-of-band edges ride either the existing
   Beneš/gather lanes *outside* the kernel (``rem_route='lanes'``: the
   precomputed addend enters as one extra input, keeping the fused round
   BIT-identical to the unfused executor), or a bucketed in-kernel
   gather over the halo window (``rem_route='inline'``: one kernel for
   everything; per-row neighbor sums are order-equivalent — exact on
   integer-valued payloads, ULP-level on floats);
4. **merges** — ``S' = -G - A + deg*avg_prev``, ``G' = -S - deg*avg +
   A_prev`` written straight from VMEM.

Tiling: the padded node vector is viewed as ``(rows, 128)`` (TPU lane
tiling); the grid walks ``block_rows``-row tiles with the previous and
next tiles loaded as halos (three BlockSpecs on one array — the
``pallas_fused`` window-pass shape), valid while the graph's RCM
bandwidth fits one tile (``max |offset| <= block_rows * 128``; the
planner guarantees it or falls back to a single whole-array tile).
Clamped boundary tiles are safe for the same reason circular rolls are:
a band mask never selects a source outside ``[0, n)``, so halo garbage
is never kept.  Vector payloads ride a trailing grid axis sharing every
mask/constant plane (batch-innermost, again the ``pallas_fused``
pipeline trick).

Off-TPU the kernel runs in Pallas **interpret mode** with identical
semantics, so the CPU test suite exercises the shipped kernel
(``tests/test_pallas_round.py`` pins bit-parity against the unfused
banded executor and the general edge kernel).  Tile shape and remainder
route are chosen by the measured-probe autotune cache in
``plan/select.py``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

LANE = 128
#: default band-tile height (rows of 128 lanes): 512 rows x 128 lanes x
#: 4 B = 256 KiB per vector plane — ~20 resident planes stay well under
#: the ~16 MiB VMEM budget
DEFAULT_BLOCK_ROWS = 512
#: sublane multiple every tile honors (f32 min tile is (8, 128))
MIN_BLOCK_ROWS = 8


def _interpret() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def _roll(x, shift: int, axis: int, size: int, interpret: bool):
    """Non-negative circular roll; pltpu.roll on TPU, jnp.roll otherwise."""
    shift %= size
    if shift == 0:
        return x
    if interpret:
        import jax.numpy as jnp

        return jnp.roll(x, shift, axis=axis)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.roll(x, shift, axis)


def _flat_roll_any(x, sh: int, nrows: int, interpret: bool):
    """Forward circular roll by ``sh`` elements of the flat row-major
    view of a ``(nrows, 128)`` tile: ``out[p] = x[(p - sh) % P]``.
    Arbitrary ``sh`` (band offsets are not powers of two): lane roll
    with a one-row carry for the sub-lane part, then a sublane roll."""
    import jax
    import jax.numpy as jnp

    sh %= nrows * LANE
    q, r = divmod(sh, LANE)
    if r:
        lr = _roll(x, r, 1, LANE, interpret)
        carry = _roll(lr, 1, 0, nrows, interpret)
        laneid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(laneid < r, carry, lr)
    return _roll(x, q, 0, nrows, interpret) if q else x


def _shift_back(x, d: int, nrows: int, interpret: bool):
    """``out[p] = x[(p + d) % P]`` — the ``jnp.roll(x, -d)`` of the
    banded executor's delivery, on the tile view.  Wrapped entries are
    never mask-selected (no edge leaves ``[0, n)``), exactly the no-wrap
    invariant the fused permutation kernels rely on."""
    return _flat_roll_any(x, (-d) % (nrows * LANE), nrows, interpret)


@dataclasses.dataclass(frozen=True, eq=False)
class FusedRoundSpec:
    """Static descriptor of one fused-round program (identity-hashed,
    jit-static — the ``BandedSpmvPlan`` convention)."""

    n: int               # real node count (RCM space)
    P: int               # padded vector length (rows * 128)
    rows: int
    block_rows: int      # tile height R; window is [prev; own; next]
    grid: int            # rows // block_rows
    offsets: tuple       # kept signed diagonals, plan order
    rem_route: str       # 'none' | 'lanes' | 'inline'
    rem_width: int       # 'inline': padded per-row remainder degree
    n_planes: int        # bitpacked band-mask planes (32 offsets each)

    @property
    def needs_window(self) -> bool:
        """Band shifts and inline gathers read beyond the own tile;
        a bandless lanes/none round is purely elementwise."""
        return bool(self.offsets) or self.rem_route == "inline"


@dataclasses.dataclass(frozen=True)
class FusedRoundLeaves:
    """Device arrays of one fused-round program (pytree leaves)."""

    planes: tuple        # n_planes x (rows, 128) uint32 band-mask bits
    rem_idx: object      # 'inline': (rows, 128, W) int32 window coords,
    #                      -1 = empty slot; else None


try:  # registered once; reimports (pytest importmode) must not re-register
    import jax as _jax

    _jax.tree_util.register_pytree_node(
        FusedRoundLeaves,
        lambda lv: ((lv.planes, lv.rem_idx), None),
        lambda _, ch: FusedRoundLeaves(planes=ch[0], rem_idx=ch[1]),
    )
except ValueError:  # pragma: no cover - double registration
    pass


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def choose_block_rows(n: int, max_abs_offset: int,
                      block_rows: int | None = None) -> int:
    """Tile height for a graph of ``n`` nodes and RCM half-bandwidth
    ``max_abs_offset``: the smallest power of two >= MIN_BLOCK_ROWS that
    (a) covers the bandwidth (every band read lands in the 3-tile
    window) and (b) caps at the whole (lane-padded) array — the
    single-tile degenerate case.  An explicit ``block_rows`` (the
    autotuner's probe knob) is validated against (a) and used as-is."""
    rows_all = _ceil_to(max(n, 1), LANE * MIN_BLOCK_ROWS) // LANE
    need = max(MIN_BLOCK_ROWS,
               -(-max_abs_offset // LANE))  # ceil(H / LANE)
    if block_rows is not None:
        r = int(block_rows)
        if r < MIN_BLOCK_ROWS or r & (r - 1):
            raise ValueError(
                f"block_rows={r} must be a power of two >= "
                f"{MIN_BLOCK_ROWS}")
        if r * LANE < max_abs_offset and r < rows_all:
            raise ValueError(
                f"block_rows={r} tile ({r * LANE} elements) cannot cover "
                f"the plan's bandwidth {max_abs_offset}; the halo window "
                "would read beyond the neighbor tiles")
        return min(r, 1 << (rows_all - 1).bit_length())
    r = MIN_BLOCK_ROWS
    while r < need or r < DEFAULT_BLOCK_ROWS // 8:
        r <<= 1
    r = min(max(r, MIN_BLOCK_ROWS), DEFAULT_BLOCK_ROWS * 8)
    # never tile finer than the array: a single whole-array tile is the
    # degenerate (and always-valid) geometry
    while r * LANE >= max(n, 1) * 2 and r > MIN_BLOCK_ROWS:
        r >>= 1
    if r * LANE < max_abs_offset:
        r = 1 << (rows_all - 1).bit_length()  # whole array, one tile
    return r


def plan_fused_round(spmv, *, block_rows: int | None = None,
                     rem_route: str = "auto") -> FusedRoundSpec:
    """Build the static spec for a :class:`~flow_updating_tpu.plan.
    banded.BandedSpmvPlan`.

    ``rem_route='auto'`` keeps the plan's remainder on its existing
    lanes (bit-exact route); 'inline' pulls a gather-remainder into the
    kernel (one kernel per round, order-equivalent sums); 'none' asserts
    the plan has no remainder."""
    offs = tuple(int(d) for d in spmv.offsets)
    H = max((abs(d) for d in offs), default=0)
    route = rem_route
    if spmv.rem_mode == "none":
        route = "none"
    elif route == "auto":
        route = "lanes"
    if route == "none" and spmv.rem_mode != "none":
        raise ValueError(
            f"rem_route='none' but the plan routes {spmv.remainder_edges} "
            "edge(s) through its remainder — use 'lanes' or 'inline'")
    if route == "inline" and spmv.rem_mode != "gather":
        raise ValueError(
            "rem_route='inline' gathers the plan's bucketed ELL "
            f"remainder in-kernel; this plan's remainder is "
            f"{spmv.rem_mode!r} — recompile with remainder='gather' or "
            "keep rem_route='lanes'")
    W = 0
    if route == "inline":
        # inline reads sit in the same halo window as the bands; the
        # exact remainder reach is validated at leaf-build time
        # (_rem_window_index raises with the fix named)
        W = max((s[1] for s in spmv.rem_bucket_shapes), default=0)
    R = choose_block_rows(spmv.n, H, block_rows)
    P = _ceil_to(max(spmv.n, 1), R * LANE)
    rows = P // LANE
    return FusedRoundSpec(
        n=spmv.n, P=P, rows=rows, block_rows=R, grid=rows // R,
        offsets=offs, rem_route=route, rem_width=W,
        n_planes=-(-len(offs) // 32),
    )


def pack_band_planes(band_masks, P: int, n_planes: int) -> list:
    """Bitpack per-offset bool band masks into flat ``(P,)`` uint32
    planes, 32 offsets each — shared by the single-device leaf builder
    and the sharded kernel's stacked planes."""
    planes = []
    for g in range(n_planes):
        plane = np.zeros(P, np.uint32)
        for j, mask in enumerate(band_masks[g * 32:(g + 1) * 32]):
            m = np.asarray(mask)
            plane[:m.shape[0]] |= m.astype(np.uint32) << j
        planes.append(plane)
    return planes


def build_fused_leaves(spmv, leaves, spec: FusedRoundSpec
                       ) -> FusedRoundLeaves:
    """Bitpack the plan's band masks (and, inline route, flatten the
    bucketed remainder ELL to window coordinates) into device leaves."""
    import jax.numpy as jnp

    rows = spec.rows
    planes = [p.reshape(rows, LANE) for p in
              pack_band_planes(leaves.band_masks, spec.P, spec.n_planes)]
    rem_idx = None
    if spec.rem_route == "inline":
        idx = _rem_window_index(spmv, leaves, spec)
        rem_idx = jnp.asarray(idx.reshape(rows, LANE, max(spec.rem_width,
                                                          1)))
    return FusedRoundLeaves(
        planes=tuple(jnp.asarray(p) for p in planes), rem_idx=rem_idx)


def _rem_window_index(spmv, leaves, spec: FusedRoundSpec) -> np.ndarray:
    """Per-row remainder neighbor matrix in WINDOW coordinates.

    The bucketed ELL (``rem_mats`` grouped by degree, ``rem_pos`` row ->
    bucket position) is flattened back to row order at the global max
    width; each index then shifts by the owning tile's window origin
    ``(tile - 1) * R * 128`` so the kernel gathers straight from its
    ``[prev; own; next]`` window.  Empty slots are -1 (gather-clamped,
    zero-masked)."""
    n, W = spec.n, max(spec.rem_width, 1)
    R = spec.block_rows
    out = np.full((spec.P, W), -1, np.int64)
    rem_pos = np.asarray(leaves.rem_pos) if leaves.rem_pos is not None \
        else None
    if rem_pos is not None and spmv.remainder_edges:
        flat = np.full((n, W), -1, np.int64)
        row0 = 0
        for m in leaves.rem_mats:
            m = np.asarray(m)
            rows_b, w = m.shape
            if w:
                blk = m.astype(np.int64)
                blk = np.where(blk >= n, -1, blk)  # n = the pad slot
                flat[row0:row0 + rows_b, :w] = blk
            row0 += rows_b
        out[:n] = flat[rem_pos]
        span = np.abs(out[:n] - np.arange(n)[:, None],
                      where=out[:n] >= 0, out=np.zeros_like(out[:n]))
        if span.max(initial=0) > R * LANE:
            raise ValueError(
                f"remainder reach {int(span.max())} exceeds the "
                f"{R * LANE}-element tile window; use rem_route='lanes' "
                "or a larger block_rows")
    tile = np.arange(spec.P, dtype=np.int64) // (R * LANE)
    origin = (tile - 1) * (R * LANE)
    out = np.where(out >= 0, out - origin[:, None], -1)
    return out.astype(np.int32)


def _pad_plane(x, P: int):
    """(M, ...) node array -> (P, ...) lane-padded (zero fill)."""
    import jax.numpy as jnp

    if x.shape[0] == P:
        return x
    pad = jnp.zeros((P - x.shape[0],) + x.shape[1:], x.dtype)
    return jnp.concatenate([x, pad])


def _to_tiles(x, spec: FusedRoundSpec):
    """(P,) or (P, D) -> (D?, rows, 128) batch-major tile view."""
    if x.ndim == 1:
        return x.reshape(1, spec.rows, LANE)
    return x.T.reshape(x.shape[1], spec.rows, LANE)


def _from_tiles(x3, like, spec: FusedRoundSpec):
    if like.ndim == 1:
        return x3.reshape(spec.P)[:like.shape[0]]
    return x3.reshape(x3.shape[0], spec.P).T[:like.shape[0]]


def _round_kernel(*refs, spec: FusedRoundSpec, interpret: bool):
    """Kernel body.  ``refs`` lays out as::

        [value{3|1}, S{3|1}, A_prev{3|1}, inv{3|1},   # windowed inputs
         G, deg, avg_prev,                            # own-tile inputs
         plane_0..plane_{k-1},                        # band-mask planes
         rem_idx?, a_rem?,                            # remainder route
         S', G', avg, A]                              # outputs (own)

    where {3|1} is prev/own/next window tiles when the spec needs a
    window, else the own tile alone."""
    import jax.numpy as jnp

    R = spec.block_rows
    w = 3 if spec.needs_window else 1
    nw = 3 * R if spec.needs_window else R

    pos = 0

    def pull_window():
        nonlocal pos
        parts = [refs[pos + j][0] for j in range(w)]
        pos += w
        return jnp.concatenate(parts, axis=0) if w > 1 else parts[0]

    v_w = pull_window()
    s_w = pull_window()
    ap_w = pull_window()
    iv_w = pull_window()
    g_o = refs[pos][0]; pos += 1
    dg_o = refs[pos][0]; pos += 1
    avp_o = refs[pos][0]; pos += 1
    planes = [refs[pos + j] for j in range(spec.n_planes)]
    pos += spec.n_planes
    rem_idx = None
    if spec.rem_route == "inline":
        rem_idx = refs[pos]; pos += 1
    a_rem = None
    if spec.rem_route == "lanes":
        a_rem = refs[pos][0]; pos += 1
    out_S, out_G, out_avg, out_A = refs[pos:pos + 4]

    # 1. fire: the elementwise avg update, on the whole window so every
    #    band shift below reads an on-chip operand
    avg_w = (v_w - s_w + ap_w) * iv_w
    own = slice(R, 2 * R) if spec.needs_window else slice(0, R)
    avg_o = avg_w[own]

    # 2. delivery: one masked shift per kept diagonal, accumulated in
    #    plan order (bit-identical to banded_neighbor_sum's loop)
    acc = jnp.zeros_like(avg_o)
    for gi, d in enumerate(spec.offsets):
        bit = ((planes[gi // 32][...] >> (gi % 32)) & 1) != 0
        shifted = _shift_back(avg_w, d, nw, interpret)[own]
        acc = acc + jnp.where(bit, shifted, 0)

    # 3. remainder
    if rem_idx is not None:
        idx = rem_idx[...]
        flat = avg_w.reshape(-1)
        gathered = flat[jnp.maximum(idx, 0)]
        acc = acc + jnp.sum(jnp.where(idx >= 0, gathered, 0), axis=-1)
    if a_rem is not None:
        acc = acc + a_rem

    # 4. merge: exactly node_round_step's ledger recurrences
    s_o = s_w[own]
    ap_o = ap_w[own]
    out_S[0] = -g_o - acc + dg_o * avp_o
    out_G[0] = -s_o - dg_o * avg_o + ap_o
    out_avg[0] = avg_o
    out_A[0] = acc


def fused_banded_round(S, G, avg_prev, A_prev, value, inv_depp1, deg,
                       fused_leaves: FusedRoundLeaves,
                       spec: FusedRoundSpec, a_rem=None, *,
                       interpret: bool | None = None):
    """One full Flow-Updating round through a single ``pallas_call``.

    All node arrays are ``(M,)`` or ``(M, D)`` with ``M <= spec.P``
    (lane-padding happens here; the banded NodeKernel sizes its padded
    vectors to ``spec.P`` so this is a no-op on the hot path).  Returns
    ``(S_next, G_next, avg, A_cur)`` shaped like the inputs."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = _interpret()
    if spec.rem_route == "lanes" and a_rem is None:
        raise ValueError("rem_route='lanes' needs the precomputed "
                         "remainder addend (a_rem)")
    P, R = spec.P, spec.block_rows
    like = S
    feat = S.shape[1:]
    B = int(np.prod(feat)) if feat else 1

    S3, G3, avp3, ap3 = (_to_tiles(_pad_plane(x, P), spec)
                         for x in (S, G, avg_prev, A_prev))
    v3 = _to_tiles(_pad_plane(value, P), spec)
    iv3 = _pad_plane(inv_depp1, P).reshape(1, spec.rows, LANE)
    dg3 = _pad_plane(deg, P).reshape(1, spec.rows, LANE)

    # batch axis maps to tile 0 for the feature-shared constant planes
    def maps(batched):
        b_of = (lambda b: b) if batched else (lambda _b: 0)
        own = lambda i, b: (b_of(b), i, 0)
        prv = lambda i, b: (b_of(b), jnp.maximum(i - 1, 0), 0)
        nxt = lambda i, b: (b_of(b), jnp.minimum(i + 1, spec.grid - 1), 0)
        return prv, own, nxt

    inputs, in_specs = [], []

    def add(arr, batched, window):
        prv, own, nxt = maps(batched)
        for mp in ((prv, own, nxt) if window and spec.needs_window
                   else (own,)):
            inputs.append(arr)
            in_specs.append(pl.BlockSpec((1, R, LANE), mp))

    add(v3, True, True)
    add(S3, True, True)
    add(ap3, True, True)
    add(iv3, False, True)
    add(G3, True, False)
    add(dg3, False, False)
    add(avp3, True, False)
    for p in fused_leaves.planes:
        inputs.append(p)
        in_specs.append(pl.BlockSpec((R, LANE), lambda i, _b: (i, 0)))
    if spec.rem_route == "inline":
        inputs.append(fused_leaves.rem_idx)
        in_specs.append(pl.BlockSpec(
            (R, LANE, fused_leaves.rem_idx.shape[-1]),
            lambda i, _b: (i, 0, 0)))
    if spec.rem_route == "lanes":
        add(_to_tiles(_pad_plane(a_rem, P), spec), True, False)
    own_out = maps(True)[1]

    shape3 = (B, spec.rows, LANE)
    out_shape = tuple(jax.ShapeDtypeStruct(shape3, S.dtype)
                      for _ in range(4))
    out = pl.pallas_call(
        lambda *refs: _round_kernel(*refs, spec=spec,
                                    interpret=interpret),
        grid=(spec.grid, B),
        in_specs=in_specs,
        out_specs=tuple(pl.BlockSpec((1, R, LANE), own_out)
                        for _ in range(4)),
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    return tuple(_from_tiles(o, like, spec) for o in out)


# ---------------------------------------------------------------------
# sharded form: one kernel per shard, halo exchange via async remote DMA
# ---------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, eq=False)
class ShardedRoundSpec:
    """Static descriptor of the one-kernel-per-shard banded round
    (identity-hashed, jit-static).  Each shard owns ``local`` contiguous
    RCM rows; ``halo_rows`` tile-rows of ``avg`` cross the wire to each
    neighbor per round — the ``make_async_remote_copy`` exchange of
    ``ops/pallas_halo.py`` composed INSIDE the fused round kernel."""

    n: int               # real node count (RCM space)
    P: int               # padded global length (num_shards * local)
    local: int           # per-shard element count (multiple of 1024)
    halo_rows: int       # exchanged tile-rows per direction
    num_shards: int
    offsets: tuple
    rem_route: str       # 'none' | 'inline'
    rem_width: int
    n_planes: int

    @property
    def local_rows(self) -> int:
        return self.local // LANE

    @property
    def halo(self) -> int:
        """Exchanged elements per direction."""
        return self.halo_rows * LANE


def _sharded_round_kernel(*refs, spec: ShardedRoundSpec, axis_name,
                          interpret: bool):
    """Kernel body: fire, START both halo DMAs, run the whole band +
    remainder accumulation on the zero-halo window while the wire is
    busy (exact for every interior row — all its reads are on-shard),
    wait, recompute through the received window and keep the boundary
    rows from it.  ``refs``::

        [value, S, A_prev, inv, G, deg, avg_prev,     # (local_rows, 128)
         plane_0..plane_{k-1}, rem_idx?,              # local slices
         S', G', avg, A, recv_lo, recv_hi,            # outputs
         avg_scratch, send_sems x2, recv_sems x2]     # scratch
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    R = spec.local_rows
    Hr = spec.halo_rows
    pos = 0
    v, s, ap, iv, g, dg, avp = refs[:7]
    pos = 7
    planes = refs[pos:pos + spec.n_planes]
    pos += spec.n_planes
    rem_idx = None
    if spec.rem_route == "inline":
        rem_idx = refs[pos]
        pos += 1
    out_S, out_G, out_avg, out_A, recv_lo, recv_hi = refs[pos:pos + 6]
    avg_ref = refs[pos + 6]
    sems = refs[pos + 7:]

    me = jax.lax.axis_index(axis_name)
    S_ = np.int32(spec.num_shards)

    # 1. fire on the own tile, land it in scratch so the DMA engines can
    #    read the boundary slices while compute continues
    avg_o = (v[...] - s[...] + ap[...]) * iv[...]
    avg_ref[...] = avg_o

    # 2. start both boundary copies: my first Hr rows feed the LEFT
    #    neighbor's high halo, my last Hr rows the RIGHT neighbor's low
    #    halo (a ring; wrapped blocks are never mask-selected, the
    #    no-wrap invariant again)
    ops = []
    for (sl, dst, d) in ((slice(0, Hr), recv_hi, -1),
                         (slice(R - Hr, R), recv_lo, +1)):
        op = pltpu.make_async_remote_copy(
            src_ref=avg_ref.at[sl],
            dst_ref=dst,
            send_sem=sems[0 if d < 0 else 1],
            recv_sem=sems[2 if d < 0 else 3],
            device_id=jax.lax.rem(me + np.int32(d) + S_, S_),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        ops.append(op)

    def accumulate(window):
        nw = R + 2 * Hr
        acc = jnp.zeros_like(avg_o)
        own = slice(Hr, Hr + R)
        for gi, d in enumerate(spec.offsets):
            bit = ((planes[gi // 32][...] >> (gi % 32)) & 1) != 0
            shifted = _shift_back(window, d, nw, interpret)[own]
            acc = acc + jnp.where(bit, shifted, 0)
        if rem_idx is not None:
            idx = rem_idx[...]
            flat = window.reshape(-1)
            gathered = flat[jnp.maximum(idx, 0)]
            acc = acc + jnp.sum(jnp.where(idx >= 0, gathered, 0),
                                axis=-1)
        return acc

    # 3. the overlap window: the full accumulation on the zero-halo
    #    view — bit-exact for every row whose reads stay on-shard.
    #    (The post-wait pass recomputes all rows and a select keeps the
    #    boundary: ~2x VPU work for the simplest possible parity story.
    #    A boundary-only post pass — O(halo_rows) instead of O(R) —
    #    halves the compute once the overlap window needs widening on
    #    real hardware; the wire bytes are unchanged either way.)
    zh = jnp.zeros((Hr, LANE), avg_o.dtype)
    acc_pre = accumulate(jnp.concatenate([zh, avg_o, zh], axis=0))

    for op in ops:
        op.wait()

    # 4. boundary rows re-read through the received halos
    acc_post = accumulate(
        jnp.concatenate([recv_lo[...], avg_o, recv_hi[...]], axis=0))
    rowid = jax.lax.broadcasted_iota(jnp.int32, avg_o.shape, 0)
    interior = (rowid >= Hr) & (rowid < R - Hr)
    acc = jnp.where(interior, acc_pre, acc_post)

    # 5. merge: node_round_step's ledger recurrences, unchanged
    out_S[...] = -g[...] - acc + dg[...] * avp[...]
    out_G[...] = -s[...] - dg[...] * avg_o + ap[...]
    out_avg[...] = avg_o
    out_A[...] = acc


def fused_sharded_round(S, G, avg_prev, A_prev, value, inv_depp1, deg,
                        planes, rem_idx, spec: ShardedRoundSpec, *,
                        axis_name, interpret: bool | None = None):
    """One fused banded round for ONE shard (call inside ``shard_map``):
    a single ``pallas_call`` that fires, exchanges ``halo`` elements of
    ``avg`` with both ring neighbors via ``make_async_remote_copy``,
    accumulates every band and remainder read, and merges the ledgers.
    All arrays are the shard's ``(local,)`` slices.  Returns
    ``(S_next, G_next, avg, A_cur)``."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret()
    R, Hr = spec.local_rows, spec.halo_rows
    t2 = lambda x: x.reshape(R, LANE)
    inputs = [t2(value), t2(S), t2(A_prev), t2(inv_depp1), t2(G),
              t2(deg), t2(avg_prev)]
    inputs += list(planes)
    if spec.rem_route == "inline":
        inputs.append(rem_idx)
    dt = S.dtype
    out_shape = (
        [jax.ShapeDtypeStruct((R, LANE), dt) for _ in range(4)]
        + [jax.ShapeDtypeStruct((Hr, LANE), dt) for _ in range(2)])
    spec_any = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
    kwargs = {}
    if not interpret:
        from flow_updating_tpu.ops.pallas_halo import (
            require_compiler_params,
        )

        kwargs["compiler_params"] = require_compiler_params(
            collective_id=1)
    out = pl.pallas_call(
        lambda *refs: _sharded_round_kernel(
            *refs, spec=spec, axis_name=axis_name, interpret=interpret),
        out_shape=tuple(out_shape),
        in_specs=[spec_any] * len(inputs),
        out_specs=tuple([spec_any] * 6),
        scratch_shapes=[pltpu.VMEM((R, LANE), dt)]
        + [pltpu.SemaphoreType.DMA] * 4,
        interpret=interpret,
        **kwargs,
    )(*inputs)
    return tuple(o.reshape(spec.local) for o in out[:4])


def fused_round_bytes(spec: FusedRoundSpec, *, dtype_bytes: int = 4,
                      features: int = 1) -> dict:
    """HBM bytes one fused round moves, vs the unfused banded executor —
    the attribution block of profile/plan manifests and the quantity
    ``regress --against`` gates (obs/profile.fused_round_report)."""
    D = max(features, 1)
    vec = spec.P * dtype_bytes
    # kernel reads: the halo-windowed planes (value, S, A_prev carry
    # the payload axis; inv is shared) are fetched once per window
    # tile, the own-tile planes (G, avg_prev payload-wide; deg shared)
    # once, plus the bitpacked masks; writes: 4 payload-wide planes
    window = 3 if spec.needs_window else 1
    reads = ((3 * D + 1) * window + (2 * D + 1)) * vec \
        + spec.n_planes * spec.P * 4
    if spec.rem_route == "inline":
        reads += spec.P * max(spec.rem_width, 1) * 4
    writes = 4 * D * vec
    fused_passes = 1
    if spec.rem_route == "lanes":
        reads += D * vec            # the precomputed remainder addend
        fused_passes += 1           # the outside avg+remainder pass
    lanes = len(spec.offsets)
    unfused = (3 * lanes + 6) * D * vec
    return {
        "bytes_per_round": int(reads + writes),
        "unfused_bytes_per_round": int(unfused),
        "passes_per_round": fused_passes,
        "unfused_passes_per_round": 3 * lanes + 6,
        "band_lanes": lanes,
        "tile_rows": spec.block_rows,
        "grid": spec.grid,
        "rem_route": spec.rem_route,
    }
