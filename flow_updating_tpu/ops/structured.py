"""Closed-form neighbor sums for regular topologies — no gather at all.

The node-collapsed fast kernel (``models/sync.py``) reduced the whole
protocol round to one adjacency SpMV, and the permutation-network path
(``ops/spmv_benes.py`` / ``ops/pallas_fused.py``) made that SpMV
gather-free for *arbitrary* graphs — ~16 HBM passes at the 1M-node
headline scale.  But the benchmark topologies themselves (BASELINE.json:
fat-tree, ring; plus grid and complete) are *regular*: their adjacency
is a product of index arithmetic, so A(x)[u] = Σ_{v∈N(u)} x[v] collapses
to reshapes, rolls, broadcasts and small-axis reductions — a stencil,
the shape TPUs were built for.  One or two streaming passes over HBM,
zero stages, zero routing plan, zero plan/compile cost beyond XLA's
normal fusion.

This replaces the reference's per-message mailbox machinery
(``/root/reference/flowupdating-collectall.py:66-85,116-125`` — one
Python actor callback per message) with *the* idiomatic TPU form: the
topology's generator proves its own structure at build time and the
round kernel exploits it, the way a conv layer never materializes its
im2col neighbor lists.

Each descriptor is a frozen, hashable dataclass (jit-static) attached to
:class:`~flow_updating_tpu.topology.graph.Topology.structure` by the
generator that built the graph.  ``neighbor_sum`` takes and returns the
``(n,)`` vector in ORIGINAL node order — the node kernel skips the ELL
degree permutation entirely on this path (there is no gather to bucket
for).  Exactness vs the generic gather form is asserted in
``tests/test_structured.py`` for every descriptor.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RingStruct:
    """Ring lattice: i ~ i±1..±k (mod n).  A(x) = Σ_d roll(x,d)+roll(x,-d).

    Only valid when ``n > 2k`` (below that the generator's declared edges
    collapse under symmetrization-dedup and the roll form would double-
    count); the generator enforces this before attaching.
    """

    n: int
    k: int

    def neighbor_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        acc = jnp.zeros_like(x)
        for d in range(1, self.k + 1):
            acc = acc + jnp.roll(x, d) + jnp.roll(x, -d)
        return acc


@dataclasses.dataclass(frozen=True)
class Grid2dStruct:
    """2-D grid, 4-neighborhood, non-periodic: pad-and-shift stencil."""

    h: int
    w: int

    @property
    def n(self) -> int:
        return self.h * self.w

    def neighbor_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        g = x.reshape(self.h, self.w)
        acc = jnp.zeros_like(g)
        if self.h > 1:
            acc = acc.at[1:].add(g[:-1]).at[:-1].add(g[1:])
        if self.w > 1:
            acc = acc.at[:, 1:].add(g[:, :-1]).at[:, :-1].add(g[:, 1:])
        return acc.reshape(-1)


@dataclasses.dataclass(frozen=True)
class CompleteStruct:
    """Complete graph: A(x) = Σx − x.  One reduction, one subtract."""

    n: int

    def neighbor_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        return jnp.sum(x) - x


@dataclasses.dataclass(frozen=True)
class Torus2dStruct:
    """2-D torus (periodic 4-neighborhood): four rolls.  Requires
    ``h, w >= 3`` (below that the wrap edges collapse under dedup)."""

    h: int
    w: int

    @property
    def n(self) -> int:
        return self.h * self.w

    def neighbor_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        g = x.reshape(self.h, self.w)
        acc = (jnp.roll(g, 1, axis=0) + jnp.roll(g, -1, axis=0)
               + jnp.roll(g, 1, axis=1) + jnp.roll(g, -1, axis=1))
        return acc.reshape(-1)


@dataclasses.dataclass(frozen=True)
class HypercubeStruct:
    """d-dimensional hypercube: neighbor i^(1<<b) for each bit b.  The
    XOR-by-bit gather is a *flip* of one axis of the ``(2,)*d`` view —
    d axis-reverses, no roll masks, no index math."""

    d: int

    @property
    def n(self) -> int:
        return 1 << self.d

    def neighbor_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        g = x.reshape((2,) * self.d)
        acc = jnp.zeros_like(g)
        for axis in range(self.d):
            acc = acc + jnp.flip(g, axis=axis)
        return acc.reshape(-1)


@dataclasses.dataclass(frozen=True)
class FatTreeStruct:
    """Al-Fares k-ary fat-tree in the generator's node layout
    (``topology/generators.py:fat_tree``): hosts ``(k, k/2, k/2)``,
    edge switches ``(k, k/2)``, aggregation switches ``(k, k/2)``,
    core switches ``(k/2, k/2)``, concatenated in that order.

    Every adjacency class is a broadcast or a small-axis reduction:

    * host (p,e,i)  ~ edge (p,e)                → broadcast
    * edge (p,e)    ~ hosts (p,e,·) + aggs (p,·) → two row sums
    * agg  (p,a)    ~ edges (p,·) + cores (a,·)  → two row sums
    * core (a,c)    ~ aggs (·,a)                 → one column sum
    """

    k: int

    @property
    def half(self) -> int:
        return self.k // 2

    @property
    def n(self) -> int:
        return self.half * self.half * self.k + self.half * self.k * 2 \
            + self.half * self.half

    def sections(self, x):
        """View a node vector as its four class sections:
        host (k, k/2, k/2), edge (k, k/2), agg (k, k/2), core (k/2, k/2)
        — the generator's layout (numpy or jnp input)."""
        k, half = self.k, self.half
        n_host = half * half * k
        n_sw = half * k
        return (
            x[:n_host].reshape(k, half, half),
            x[n_host:n_host + n_sw].reshape(k, half),
            x[n_host + n_sw:n_host + 2 * n_sw].reshape(k, half),
            x[n_host + 2 * n_sw:].reshape(half, half),
        )

    @staticmethod
    def pod_local_sums(xh, xe, xa, xc):
        """The stencil terms of any contiguous block of pods (``xc`` is
        the full core grid — replicated in the pod-sharded kernel).
        Returns (a_host, a_edge, a_agg, a_core_partial) where
        ``a_core_partial[a] = Σ_{p∈block} xa[p, a]`` — summing partials
        over all blocks (or psum over a pod mesh axis,
        ``parallel/structured_sharded.py``) gives the core column sum."""
        kb, h = xe.shape
        a_host = jnp.broadcast_to(xe[:, :, None], (kb, h, h))
        a_edge = xh.sum(axis=2) + xa.sum(axis=1, keepdims=True)
        a_agg = xe.sum(axis=1, keepdims=True) + xc.sum(axis=1)[None, :]
        return a_host, a_edge, a_agg, xa.sum(axis=0)

    def neighbor_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        xh, xe, xa, xc = self.sections(x)
        a_host, a_edge, a_agg, part = self.pod_local_sums(xh, xe, xa, xc)
        a_core = jnp.broadcast_to(part[:, None], xc.shape)
        return jnp.concatenate([
            a_host.reshape(-1), a_edge.reshape(-1),
            a_agg.reshape(-1), a_core.reshape(-1),
        ])


def structured_neighbor_sum(x: jnp.ndarray, struct) -> jnp.ndarray:
    """Apply a structure descriptor to the first ``struct.n`` entries of a
    (possibly padded) vector; padding slots get neighbor sum 0, matching
    the generic path's zero-slot convention."""
    n = struct.n
    a = struct.neighbor_sum(x[:n])
    if x.shape[0] == n:
        return a
    return jnp.concatenate([a, jnp.zeros((x.shape[0] - n,), x.dtype)])
