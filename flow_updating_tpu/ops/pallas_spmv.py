"""Pallas TPU kernel for the bucketed neighbor-sum (adjacency SpMV).

The node-collapsed fast kernel's one graph op is ``A(x)[u] = sum over u's
neighbors of x[v]`` in degree-bucketed ELL form (Topology.ell_buckets): per
bucket, gather ``x`` by a dense ``(rows, width)`` index matrix and reduce
rows.  The XLA lowering streams both the index matrix and the gathered
values through HBM; this Pallas kernel instead keeps the **whole x vector
resident in VMEM** across the row-block grid (4 bytes/node — ~4 MB at 1M
nodes, comfortably inside the ~16 MB VMEM) and streams only the index
blocks, so each row block does VMEM-local gathers + a row reduction with no
HBM round-trip for the gathered operand.

Falls back to interpreter mode off-TPU (tests run it on CPU); the public
entry :func:`neighbor_sum_pallas` is a drop-in for
``flow_updating_tpu.models.sync.neighbor_sum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# jax.experimental.pallas.tpu registers TPU lowering rules at import time,
# which fails in CPU-pinned environments that deregister the TPU plugin
# (tests/conftest.py) — import it only when compiling for a real TPU.

# Rows of the index matrix processed per grid step.  8 sublanes x 128 lanes
# is the f32 VMEM tile; index blocks are (BLOCK_ROWS, width).
BLOCK_ROWS = 256


def _spmv_bucket_kernel(x_ref, idx_ref, out_ref):
    # x_ref: (M1,) full padded vector (VMEM-resident, same block every step)
    # idx_ref: (BLOCK_ROWS, W) int32 neighbor slots (M1 - 1 = zero slot)
    # out_ref: (BLOCK_ROWS, 1) row sums
    idx = idx_ref[...]
    vals = x_ref[idx]            # VMEM-local dynamic gather
    out_ref[...] = jnp.sum(vals, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _spmv_bucket(xp, mat, interpret: bool):
    rows, w = mat.shape
    grid = rows // BLOCK_ROWS if rows % BLOCK_ROWS == 0 else -1
    assert grid > 0, "caller pads rows to BLOCK_ROWS"
    if interpret:
        x_spec = pl.BlockSpec()  # whole array
        mem = {}
    else:
        from jax.experimental.pallas import tpu as pltpu

        x_spec = pl.BlockSpec(memory_space=pltpu.VMEM)  # full x, every step
        mem = {"memory_space": pltpu.VMEM}
    return pl.pallas_call(
        _spmv_bucket_kernel,
        grid=(grid,),
        in_specs=[
            x_spec,
            pl.BlockSpec((BLOCK_ROWS, w), lambda i: (i, 0), **mem),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, 1), lambda i: (i, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((rows, 1), xp.dtype),
        interpret=interpret,
    )(xp, mat)[:, 0]


def neighbor_sum_pallas(x: jnp.ndarray, mats: tuple,
                        interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in for :func:`flow_updating_tpu.models.sync.neighbor_sum`.

    Requires every bucket's row count to be a multiple of ``BLOCK_ROWS``
    (build the :class:`~flow_updating_tpu.models.sync.NodeKernel` with
    ``row_multiple=BLOCK_ROWS`` — or a multiple — to guarantee it).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    xp = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])
    parts = []
    for m in mats:
        if m.shape[1] == 0:
            parts.append(jnp.zeros((m.shape[0],), x.dtype))
        else:
            parts.append(_spmv_bucket(xp, m, interpret))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]
