"""Gather/scatter-free per-node reductions and broadcasts for the edge kernel.

``cfg.segment_impl='benes'`` — the faithful-mode counterpart of the node
kernel's permutation-network SpMV.  The edge kernel's hot graph ops are:

* **segment reduce** (sum/min/max/all over each node's out-edges) — XLA
  lowers ``jax.ops.segment_*`` to scatters, which serialize on TPU;
* **broadcast** (``x[src]``: node value to every out-edge) — a dynamic
  gather, a scalar loop on TPU.

Both are static graph structure, so both become switching circuits
(:mod:`flow_updating_tpu.ops.permute`):

    reduce(x)    = extract_benes( segmented_scan(x) )[:N]
    broadcast(v) = fill_forward( place_benes(v) )[:E]

The segmented Hillis-Steele scan needs NO stored masks at all — stage
k's condition is ``edge_rank >= 2**k`` and fill-forward's is bit k of
``edge_rank``, both computed on the fly from one static (P,) int32 array
and fused into the select by XLA.  Only the two Beneš permutations
(row-end -> node extraction; node -> row-head placement) carry stored
masks, planned once per topology:

* extraction maps each deg>0 node's row end to the node id, and each
  deg-0 node to a dedicated identity slot in the padding region
  (initialized to the reduction's identity, untouched by the scan since
  its distance is 0);
* placement maps node v to ``row_start[v]`` (its run head); every
  position's run head is a row start, so fill-forward never reads a
  junk slot.

All stages are dtype-agnostic (roll/flip/select), so int32 drain keys
ride the network unconverted.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.ops.permute import (
    StagePlan,
    apply_stages,
    benes_plan,
    next_pow2,
)


@dataclasses.dataclass(frozen=True, eq=False)
class SegmentedPlan:
    """Host-side plan (identity-hashed: rides jit as a static field; the
    Beneš masks and the dist array travel as pytree leaves)."""

    N: int               # node count (reduce output length)
    E: int               # directed edge count (broadcast output length)
    P: int               # power-of-two circuit width >= E + #deg0
    scan_bits: int       # stages in the segmented scan (bit_length(maxdeg-1))
    fill_bits: int       # stages in fill-forward (same bound)
    extract: StagePlan   # row-end -> node id permutation
    place: StagePlan     # node id -> row-head permutation
    extract_fused: object = None   # pallas_fused.FusedPlan, or None
    place_fused: object = None     # (segment_impl='benes_fused')
    geom: object = None            # pallas_fused.Geometry: fused scan/fill

    def device_leaves(self):
        """(extract_masks, place_masks) ready for TopoArrays."""
        if self.extract_fused is not None:
            from flow_updating_tpu.ops.pallas_fused import device_mask_planes

            return (device_mask_planes(self.extract, self.extract_fused),
                    device_mask_planes(self.place, self.place_fused))
        return (self.extract.device_masks(), self.place.device_masks())


def plan_segments(row_start: np.ndarray, out_deg: np.ndarray,
                  edge_rank: np.ndarray,
                  fused: bool = False) -> tuple[SegmentedPlan, np.ndarray]:
    """Build the plan from the topology's CSR structure.

    Returns ``(plan, dist)`` where ``dist`` is the (P,) int32 array the
    on-the-fly scan/fill masks derive from (edge_rank padded with 0).
    ``fused=True`` runs both permutations through the fused-Pallas
    executor when the circuit is large enough."""
    N = len(out_deg)
    E = len(edge_rank)
    deg0 = np.flatnonzero(out_deg == 0)
    P = next_pow2(E + len(deg0))
    maxdeg = int(out_deg.max()) if N else 1
    bits = max(maxdeg - 1, 0).bit_length()

    dist = np.zeros(P, np.int32)
    dist[:E] = edge_rank

    def complete(partial: np.ndarray) -> np.ndarray:
        """Fill the -1 outputs of a partial injective map with the unused
        sources (any order) to make a full permutation."""
        used = np.zeros(len(partial), bool)
        used[partial[partial >= 0]] = True
        out = partial.copy()
        out[out < 0] = np.flatnonzero(~used)
        return out

    # extraction: out[u] = scan[row_end[u]] (deg>0) | identity slot (deg0);
    # outputs [N, P) soak up the remaining sources (sliced off)
    perm = np.full(P, -1, np.int64)
    pos = np.asarray(out_deg, np.int64) > 0
    perm[np.flatnonzero(pos)] = row_start[1:][pos] - 1
    perm[deg0] = E + np.arange(len(deg0), dtype=np.int64)
    extract = benes_plan(complete(perm))

    # placement: out[row_start[v]] = x[v] for deg>0 v; all other outputs
    # take leftover sources (junk — never a run head, never read)
    perm2 = np.full(P, -1, np.int64)
    perm2[row_start[:-1][pos]] = np.flatnonzero(pos)
    place = benes_plan(complete(perm2))

    extract_fused = place_fused = geom = None
    if fused:
        from flow_updating_tpu.ops.pallas_fused import (
            MIN_P,
            geometry,
            halo_rows,
            plan_fused,
        )

        if P >= MIN_P:
            extract_fused = plan_fused(extract)
            place_fused = plan_fused(place)
            g = geometry(P)
            # the scan/fill runs fuse only while their summed halo fits
            # the window (pallas_fused.halo_rows — the same rule the
            # passes enforce); falls back to the XLA loop otherwise
            if halo_rows(1 << k for k in range(bits)) <= g.block_rows:
                geom = g
    plan = SegmentedPlan(N=N, E=E, P=P, scan_bits=bits, fill_bits=bits,
                         extract=extract, place=place,
                         extract_fused=extract_fused,
                         place_fused=place_fused, geom=geom)
    return plan, dist


def _apply(z, stages: StagePlan, fused_plan, masks):
    """One permutation application: fused-Pallas when planned, XLA
    stage form otherwise."""
    if fused_plan is not None:
        from flow_updating_tpu.ops.pallas_fused import apply_fused

        return apply_fused(z, fused_plan, masks)
    return apply_stages(z, stages, masks)


def _identity_for(op: str, dtype):
    import jax.numpy as jnp

    if op == "sum":
        return jnp.zeros((), dtype)
    if op == "all":
        return jnp.ones((), jnp.bool_)
    info = (jnp.iinfo(dtype) if jnp.issubdtype(dtype, jnp.integer)
            else jnp.finfo(dtype))
    return jnp.asarray(info.max if op == "min" else info.min, dtype)


def _combine(op: str):
    import jax.numpy as jnp

    return {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum,
            "all": jnp.logical_and}[op]


def _to_lanes(x, plan_len: int, fill, E_or_N: int):
    """Embed an ``(L, F...)`` edge/node array into the circuit width as
    ``(F, P)`` feature lanes (just ``(P,)`` for the scalar ``(L,)``
    case) — every network stage operates over the LAST axis, so feature
    lanes of a vector payload ride one batched application, exactly like
    the multi-lane helpers below.  Returns ``(z, F)``."""
    import jax.numpy as jnp

    F = x.shape[1:]
    if not F:
        z = jnp.full((plan_len,), fill, x.dtype)
        return z.at[:E_or_N].set(x), F
    lanes = x.reshape(x.shape[0], -1).T          # (prod(F), L)
    z = jnp.full((lanes.shape[0], plan_len), fill, x.dtype)
    return z.at[:, :E_or_N].set(lanes), F


def _from_lanes(z, F, out_len: int):
    """Inverse of :func:`_to_lanes`: ``(F, P)`` lanes -> ``(out_len,
    F...)`` (the scalar case degenerates to ``z[:out_len]``)."""
    return z[..., :out_len].T.reshape((out_len,) + F)


def seg_reduce(x, op: str, plan: SegmentedPlan, dist, extract_masks):
    """Per-node reduction of the ``(E,)`` (or ``(E, F)`` vector-payload)
    edge array ``x`` -> ``(N,)`` (or ``(N, F)``)."""
    import jax.numpy as jnp

    ident = _identity_for(op, x.dtype)
    comb = _combine(op)
    z, F = _to_lanes(x, plan.P, ident, plan.E)
    if plan.geom is not None and plan.scan_bits:
        from flow_updating_tpu.ops.pallas_fused import segscan_pass

        dists = tuple(1 << k for k in range(plan.scan_bits))
        if op == "all":
            # Mosaic-friendly: booleans scan as int32 min (ident 1)
            z = segscan_pass(z.astype(jnp.int32), dist, dists, "min",
                             plan.geom) != 0
        else:
            z = segscan_pass(z, dist, dists, op, plan.geom)
    else:
        for k in range(plan.scan_bits):
            d = 1 << k
            taken = jnp.where(dist >= d, jnp.roll(z, d, axis=-1), ident)
            z = comb(z, taken)
    out = _apply(z, plan.extract, plan.extract_fused, extract_masks)
    return _from_lanes(out, F, plan.N)


def extract_row_ends(x, plan: SegmentedPlan, extract_masks):
    """(E,) (or (E, F)) edge array -> (N,) (or (N, F)) values at each
    node's LAST out-edge (the ``x[row_start[1:] - 1]`` gather; deg-0
    nodes read 0)."""
    z, F = _to_lanes(x, plan.P, 0, plan.E)
    return _from_lanes(
        _apply(z, plan.extract, plan.extract_fused, extract_masks),
        F, plan.N)


def seg_reduce_multi(xs_ops, plan: SegmentedPlan, dist, extract_masks):
    """Several per-node reductions sharing one batched extraction.

    ``xs_ops``: sequence of ``(x (E,), op)``.  All 'sum' lanes run as one
    batched scan pass; 'all' scans as int-min; the scanned lanes then
    ride ONE batched extraction application (the expensive ~2log2P-stage
    part), sharing its mask-plane traffic.  Returns the (N,) results in
    input order.  Falls back to per-call :func:`seg_reduce` when the
    plan has no fused geometry.
    """
    import jax.numpy as jnp

    if plan.geom is None or not plan.scan_bits:
        return [seg_reduce(x, op, plan, dist, extract_masks)
                for x, op in xs_ops]
    from flow_updating_tpu.ops.pallas_fused import segscan_pass

    dt = jnp.result_type(*[x.dtype for x, _ in xs_ops], jnp.float32)
    dists = tuple(1 << k for k in range(plan.scan_bits))

    lanes = [None] * len(xs_ops)
    # only lanes already in the common dtype batch together: integer
    # sums would round above 2^24 in f32, and a f32 lane scanned in a
    # wider common dtype (mixed f32/f64 inputs) then cast back would
    # break bit-equality with the per-call path — both ride the exact
    # per-call path below, like min/max
    sums = [(i, x) for i, (x, op) in enumerate(xs_ops)
            if op == "sum" and x.dtype == dt]
    if sums:
        z = jnp.stack([
            jnp.zeros((plan.P,), dt).at[: plan.E].set(x.astype(dt))
            for _, x in sums
        ])
        z = segscan_pass(z, dist, dists, "sum", plan.geom)
        for (i, _), zi in zip(sums, z):
            lanes[i] = zi
    for i, (x, op) in enumerate(xs_ops):
        if op == "sum":
            continue
        if op == "all":
            # booleans scan exactly as a float min over {0, 1}
            z = jnp.ones((plan.P,), dt).at[: plan.E].set(
                x.astype(jnp.int32).astype(dt))
            lanes[i] = segscan_pass(z, dist, dists, "min", plan.geom)
        else:
            # min/max over arbitrary values could lose precision in the
            # shared float lane dtype (e.g. int32 keys in f32) — run the
            # exact per-op path and splice its result in afterwards
            lanes[i] = None
    batched = [ln for ln in lanes if ln is not None]
    if not batched:
        return [seg_reduce(x, op, plan, dist, extract_masks)
                for x, op in xs_ops]
    out = _apply(jnp.stack(batched), plan.extract, plan.extract_fused,
                 extract_masks)[:, : plan.N]
    results = []
    j = 0
    for i, (x, op) in enumerate(xs_ops):
        if lanes[i] is None:
            results.append(seg_reduce(x, op, plan, dist, extract_masks))
            continue
        r = out[j]
        j += 1
        if op == "all":
            r = r != 0
        else:
            r = r.astype(x.dtype)
        results.append(r)
    return results


def broadcast_multi(vs, plan: SegmentedPlan, dist, place_masks):
    """Several node->edge broadcasts through one batched placement +
    fill-forward.  ``vs``: sequence of (N,) arrays; returns the (E,)
    results in input order."""
    import jax.numpy as jnp

    if plan.geom is None:
        return [broadcast(v, plan, dist, place_masks) for v in vs]
    from flow_updating_tpu.ops.pallas_fused import fill_pass

    dt = jnp.result_type(*[v.dtype for v in vs], jnp.float32)
    z = jnp.stack([
        jnp.zeros((plan.P,), dt).at[: plan.N].set(v.astype(dt)) for v in vs
    ])
    z = _apply(z, plan.place, plan.place_fused, place_masks)
    if plan.fill_bits:
        dists = tuple(1 << k for k in range(plan.fill_bits))
        z = fill_pass(z, dist, dists, plan.geom)
    out = z[:, : plan.E]
    results = []
    for v, r in zip(vs, out):
        if v.dtype == jnp.bool_:
            results.append(r > 0.5)
        else:
            results.append(r.astype(v.dtype))
    return results


def broadcast(v, plan: SegmentedPlan, dist, place_masks):
    """Node array (N,) (or (N, F) vector payload) -> per-out-edge array
    (E,) (or (E, F)) (the ``v[src]`` gather, gather-free)."""
    import jax.numpy as jnp

    z, F = _to_lanes(v, plan.P, 0, plan.N)
    z = _apply(z, plan.place, plan.place_fused, place_masks)
    if plan.geom is not None and plan.fill_bits:
        from flow_updating_tpu.ops.pallas_fused import fill_pass

        dists = tuple(1 << k for k in range(plan.fill_bits))
        z = fill_pass(z, dist, dists, plan.geom)
    else:
        for k in range(plan.fill_bits):
            d = 1 << k
            z = jnp.where((dist >> k) & 1 != 0, jnp.roll(z, d, axis=-1), z)
    return _from_lanes(z, F, plan.E)
