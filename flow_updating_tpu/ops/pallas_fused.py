"""Fused permutation-network passes: many stages per HBM round trip.

The XLA form of a Beneš/barrel-shifter stage (`permute.apply_stages`)
materializes two `jnp.roll`s plus selects per stage — ~600 us per stage
at 8M elements on a v5e, so the ~91-stage network costs ~47 ms/round.
But a stage is ~40 us of *compute*; the rest is HBM traffic.  This
module executes the same stages inside Pallas kernels so that one pass
over HBM applies up to 32 stages (measured ~125 us/pass + ~17 us/stage
at 33 MB):

* the flat ``(P,)`` array is viewed as ``(P/128, 128)`` — TPU's native
  lane tiling; a power-of-two element distance ``d`` becomes a row
  distance ``d/128`` (d >= 128) or a lane distance (d < 128);
* **local pass**: a run of swap stages whose pair blocks fit inside one
  ``R``-row grid block.  The butterfly ``x[p] <- x[p ^ d]`` is two
  ``pltpu.roll``s + selects in VMEM (rows) or lane-rolls (d < 128);
* **window pass**: a run of roll stages.  Rolls move data forward
  across block boundaries, so the kernel loads the previous block as a
  halo (two input BlockSpecs on the same array) and applies the run on
  the 2R-row window; valid as long as the run's total row distance is
  <= R (halo-consumption argument in :func:`plan_fused`);
* **wide pass**: a single stage whose distance exceeds the block.
  Because block size divides the distance, the partner element lives at
  the same offset of a partner *block*: a second input BlockSpec with
  index map ``i ^ (d/B)`` (swap) or ``max(i - d/B, 0)`` (roll) — one
  select, no roll at all.

Stage masks for a local/window pass are bitpacked on the host into one
``uint32`` plane (bit j = stage j of the pass), so a 32-stage pass
reads 4 mask bytes per element instead of 32.

Planner input is the host :class:`flow_updating_tpu.ops.permute.StagePlan`;
results are bit-identical to `apply_stages` (asserted in tests, and on
real TPU by the microbench).  Off-TPU the kernels run in Pallas
interpret mode with `jnp.roll` (tests); production CPU paths should
keep using the XLA form.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flow_updating_tpu.ops.permute import StagePlan

LANE = 128
MAX_STAGES_PER_PASS = 32
DEFAULT_BLOCK_ROWS = 2048
# below this the (rows, 128) view degenerates; callers should use the
# XLA apply_stages path instead
MIN_P = LANE * 8


@dataclasses.dataclass(frozen=True, eq=False)
class PassSpec:
    """One HBM round trip.  ``eq=False``: identity-hashed, jit-static."""

    kind: str            # 'local' | 'window' | 'wide_swap' | 'wide_roll'
    #                      | 'wide_swap2' | 'wide_roll2' (two merged stages)
    dists: tuple         # element distances, in stage order
    block_dist: int      # wide passes: partner distance in blocks
    block_dist2: int = 0  # wide2 passes: second stage's block distance


@dataclasses.dataclass(frozen=True, eq=False)
class Geometry:
    """Block geometry shared by every pass flavor."""

    P: int
    rows: int
    block_rows: int
    grid: int


@dataclasses.dataclass(frozen=True, eq=False)
class FusedPlan:
    """Device-applicable pass sequence for one :class:`StagePlan`."""

    geom: Geometry
    passes: tuple        # of PassSpec

    @property
    def P(self):
        return self.geom.P

    @property
    def rows(self):
        return self.geom.rows

    @property
    def block_rows(self):
        return self.geom.block_rows

    @property
    def grid(self):
        return self.geom.grid

    def device_masks(self):
        """Placeholder for interface parity; masks are built by
        :func:`pack_masks` and travel as pytree leaves."""
        raise TypeError("use pack_masks(stage_plan, fused_plan)")


def _classify(kind: str, d: int, R: int) -> str:
    """Pass flavor for one stage at block height ``R`` rows."""
    rowd = d // LANE
    if kind == "swap":
        # pair block of 2*rowd rows must fit in (and align to) R rows
        return "local" if (d < LANE or 2 * rowd <= R) else "wide_swap"
    return "window" if rowd < R else "wide_roll"


def plan_fused(plan: StagePlan,
               block_rows: int = DEFAULT_BLOCK_ROWS) -> FusedPlan:
    """Segment ``plan``'s stages into fused passes, preserving order.

    Halo-consumption rule for window passes: let v be the first valid
    row of the [prev; own] window (v=0 after load).  A roll at row
    distance dr reads dr rows below, so v += dr; the own part (rows
    >= R) stays exact while sum(dr) <= R.  Masked-on reads never hit
    the invalid prefix because the stage masks never select a
    wrapped-around source (spread/fill plans guarantee it — see
    permute.spread_plan / fill_forward_stages).
    """
    geom = geometry(plan.n, block_rows)
    P, rows, R = geom.P, geom.rows, geom.block_rows

    passes = []
    cur_kind, cur_dists, cur_halo = None, [], 0

    def flush():
        nonlocal cur_kind, cur_dists, cur_halo
        if cur_dists:
            passes.append(PassSpec(kind=cur_kind, dists=tuple(cur_dists),
                                   block_dist=0))
        cur_kind, cur_dists, cur_halo = None, [], 0

    for d, kind in zip(plan.dists, plan.kinds):
        if kind == "swap" and d & (d - 1):
            # the in-block butterfly and the wide xor partner both rely
            # on power-of-two pair distances (true of every Benes plan)
            raise ValueError(f"swap distance {d} is not a power of two")
        if kind == "roll" and d >= LANE and d % LANE:
            # the row-roll form shifts whole rows; a distance that is not
            # a multiple of the lane width would be silently truncated
            raise ValueError(
                f"roll distance {d} >= {LANE} must be a multiple of {LANE}")
        flavor = _classify(kind, d, R)
        if flavor in ("wide_swap", "wide_roll"):
            if (d // LANE) % R:
                raise ValueError(
                    f"wide stage distance {d} is not a multiple of the "
                    f"block ({R * LANE} elements)")
            flush()
            passes.append(PassSpec(kind=flavor, dists=(d,),
                                   block_dist=(d // LANE) // R))
            continue
        # halo cost: rolls consume their row distance (lane rolls carry
        # one row); local swaps are exact within aligned pair blocks
        halo = 0
        if flavor == "window":
            halo = max(d // LANE, 1)
        if (cur_kind != flavor
                or len(cur_dists) >= MAX_STAGES_PER_PASS
                or (flavor == "window" and cur_halo + halo > R)):
            flush()
            cur_kind = flavor
        cur_dists.append(d)
        cur_halo += halo
    flush()
    # pairwise-merge adjacent single-stage wide passes of the same kind:
    # 2 stages per HBM round trip via 4 input blocks (source offsets
    # {0, D1, D2, D1+D2}) instead of 2x(2 blocks) — fewer passes AND
    # less traffic
    merged = []
    for ps in passes:
        prev = merged[-1] if merged else None
        if (prev is not None
                and prev.kind in ("wide_swap", "wide_roll")
                and ps.kind == prev.kind):
            merged[-1] = PassSpec(
                kind=prev.kind + "2",
                dists=prev.dists + ps.dists,
                block_dist=prev.block_dist,
                block_dist2=ps.block_dist,
            )
            continue
        merged.append(ps)
    return FusedPlan(geom=geom, passes=tuple(merged))


def pack_masks(plan: StagePlan, fused: FusedPlan):
    """Host-side mask planes, one per pass, in pass order.

    local/window passes: ``(rows, 128) uint32``, bit j = stage j of the
    pass.  wide passes: ``(rows, 128) int8``.
    """
    planes = []
    s = 0
    for ps in fused.passes:
        n_stages = len(ps.dists)
        stage_masks = plan.masks[s: s + n_stages]
        if ps.kind in ("window", "wide_roll", "wide_roll2"):
            # The kernels clamp/duplicate block 0 where apply_stages'
            # jnp.roll wraps circularly, so a roll-stage mask that selects
            # a wrapped-around source (p < d reading from p - d + P) would
            # silently corrupt data.  All in-repo plan producers satisfy
            # this no-wrap invariant; verify it so a violating plan fails
            # loudly here instead.
            for j, (d, m) in enumerate(zip(ps.dists, stage_masks)):
                if m[:d].any():
                    raise ValueError(
                        f"roll stage {s + j} (distance {d}) selects a "
                        f"wrapped-around source: mask is set below index "
                        f"{d}; fused kernels do not implement circular "
                        f"wrap (use the XLA apply_stages path)")
        s += n_stages
        if ps.kind in ("local", "window"):
            plane = np.zeros(fused.P, np.uint32)
            for j, m in enumerate(stage_masks):
                plane |= m.astype(np.uint32) << j
        elif ps.kind in ("wide_swap2", "wide_roll2"):
            plane = (stage_masks[0].astype(np.int8)
                     | (stage_masks[1].astype(np.int8) << 1))
        else:
            plane = stage_masks[0].astype(np.int8)
        planes.append(plane.reshape(fused.rows, LANE))
    assert s == len(plan.masks), "pass segmentation lost stages"
    return tuple(planes)


def device_mask_planes(plan: StagePlan, fused: FusedPlan):
    import jax.numpy as jnp

    return tuple(jnp.asarray(p) for p in pack_masks(plan, fused))


def _interpret() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def _roll(x, shift: int, axis: int, size: int, interpret: bool):
    """Non-negative circular roll; pltpu.roll on TPU, jnp.roll otherwise."""
    shift %= size
    if shift == 0:
        return x
    if interpret:
        import jax.numpy as jnp

        return jnp.roll(x, shift, axis=axis)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.roll(x, shift, axis)


def _apply_stage_in_block(x, bit, d: int, kind: str, nrows: int,
                          interpret: bool):
    """One stage on a VMEM-resident ``(nrows, 128)`` window.

    ``bit`` is the stage's bool mask for the window.  Flat semantics
    (matching permute.apply_stages on the flattened array):

    * swap, d >= 128: butterfly on the row index at dr = d/128;
    * swap, d < 128: butterfly on the lane index;
    * roll, d >= 128: take the value d/128 rows up;
    * roll, d < 128: lane roll with a one-row carry into lanes < d.
    """
    import jax
    import jax.numpy as jnp

    shape = x.shape
    if kind == "swap":
        if d >= LANE:
            dr = d // LANE
            rowid = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
            hi = (rowid & dr) != 0
            fwd = _roll(x, dr, 0, nrows, interpret)
            bwd = _roll(x, nrows - dr, 0, nrows, interpret)
        else:
            laneid = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
            hi = (laneid & d) != 0
            fwd = _roll(x, d, 1, LANE, interpret)
            bwd = _roll(x, LANE - d, 1, LANE, interpret)
        return jnp.where(bit & hi, fwd, jnp.where(bit & ~hi, bwd, x))
    # roll kind: value comes from d elements to the left (flat order)
    return jnp.where(bit, _flat_roll(x, d, nrows, interpret), x)


def _local_pass(x3, mask_plane, ps: PassSpec, fused: FusedPlan,
                interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    R = fused.block_rows

    def kern(x_ref, m_ref, o_ref):
        x = x_ref[0]
        m = m_ref[...]
        for j, d in enumerate(ps.dists):
            bit = ((m >> j) & 1) != 0
            x = _apply_stage_in_block(x, bit, d, "swap", R, interpret)
        o_ref[0] = x

    # batch axis innermost: consecutive grid steps share the mask
    # block index, so the pipeline skips its re-fetch across lanes
    own = lambda i, b: (b, i, 0)
    mown = lambda i, _b: (i, 0)
    return pl.pallas_call(
        kern,
        grid=(fused.grid, x3.shape[0]),
        in_specs=[pl.BlockSpec((1, R, LANE), own),
                  pl.BlockSpec((R, LANE), mown)],
        out_specs=pl.BlockSpec((1, R, LANE), own),
        out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
        interpret=interpret,
    )(x3, mask_plane)


def _window_pass(x3, mask_plane, ps: PassSpec, fused: FusedPlan,
                 interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    R = fused.block_rows

    def kern(xp_ref, xo_ref, mp_ref, mo_ref, o_ref):
        w = jnp.concatenate([xp_ref[0], xo_ref[0]], axis=0)
        m = jnp.concatenate([mp_ref[...], mo_ref[...]], axis=0)
        for j, d in enumerate(ps.dists):
            bit = ((m >> j) & 1) != 0
            w = _apply_stage_in_block(w, bit, d, "roll", 2 * R, interpret)
        o_ref[0] = w[R:]

    prev = lambda i, b: (b, jnp.maximum(i - 1, 0), 0)
    own = lambda i, b: (b, i, 0)
    mprev = lambda i, _b: (jnp.maximum(i - 1, 0), 0)
    mown = lambda i, _b: (i, 0)
    return pl.pallas_call(
        kern,
        grid=(fused.grid, x3.shape[0]),
        in_specs=[pl.BlockSpec((1, R, LANE), prev),
                  pl.BlockSpec((1, R, LANE), own),
                  pl.BlockSpec((R, LANE), mprev),
                  pl.BlockSpec((R, LANE), mown)],
        out_specs=pl.BlockSpec((1, R, LANE), own),
        out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
        interpret=interpret,
    )(x3, x3, mask_plane, mask_plane)


def _wide_pass(x3, mask_plane, ps: PassSpec, fused: FusedPlan,
               interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    R = fused.block_rows
    D = ps.block_dist

    def kern(a_ref, b_ref, m_ref, o_ref):
        o_ref[0] = jnp.where(m_ref[...] != 0, b_ref[0], a_ref[0])

    if ps.kind == "wide_swap":
        partner = lambda i, b: (b, i ^ D, 0)
    else:  # wide_roll: value comes D blocks up; wrapped sources are
        # never mask-selected, so clamping at 0 is safe
        partner = lambda i, b: (b, jnp.maximum(i - D, 0), 0)
    own = lambda i, b: (b, i, 0)
    mown = lambda i, _b: (i, 0)
    return pl.pallas_call(
        kern,
        grid=(fused.grid, x3.shape[0]),
        in_specs=[pl.BlockSpec((1, R, LANE), own),
                  pl.BlockSpec((1, R, LANE), partner),
                  pl.BlockSpec((R, LANE), mown)],
        out_specs=pl.BlockSpec((1, R, LANE), own),
        out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
        interpret=interpret,
    )(x3, x3, mask_plane)


def _wide2_pass(x3, mask_plane, ps: PassSpec, fused: FusedPlan,
                interpret: bool):
    """Two merged wide stages in one round trip.

    Dataflow: stage 1 maps p from p+off1(p) (off1 in {0, D1} by mask
    bit 0), stage 2 from p+off2(p) (off2 in {0, D2} by bit 1), so the
    final source block offset is one of {0, D1, D2, D1+D2} (roll; xor
    for swaps).  The kernel reconstructs stage 1's result at both the
    own block and the D2-partner block — the latter needs stage 1's
    mask bit at that partner, hence the second mask spec."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    R = fused.block_rows
    D1, D2 = ps.block_dist, ps.block_dist2

    def kern(x0_ref, x1_ref, x2_ref, x12_ref, m_ref, m2_ref, o_ref):
        m1_own = (m_ref[...] & 1) != 0
        m1_shift = (m2_ref[...] & 1) != 0
        m2_own = (m_ref[...] & 2) != 0
        s1_own = jnp.where(m1_own, x1_ref[0], x0_ref[0])
        s1_shift = jnp.where(m1_shift, x12_ref[0], x2_ref[0])
        o_ref[0] = jnp.where(m2_own, s1_shift, s1_own)

    if ps.kind == "wide_swap2":
        at = lambda D: (lambda i, b, D=D: (b, i ^ D, 0))
        mat = lambda i, _b: (i ^ D2, 0)
    else:
        at = lambda D: (lambda i, b, D=D: (b, jnp.maximum(i - D, 0), 0))
        mat = lambda i, _b: (jnp.maximum(i - D2, 0), 0)
    own = lambda i, b: (b, i, 0)
    mown = lambda i, _b: (i, 0)
    return pl.pallas_call(
        kern,
        grid=(fused.grid, x3.shape[0]),
        in_specs=[pl.BlockSpec((1, R, LANE), own),
                  pl.BlockSpec((1, R, LANE), at(D1)),
                  pl.BlockSpec((1, R, LANE), at(D2)),
                  pl.BlockSpec((1, R, LANE), at(D1 + D2)
                               if ps.kind == "wide_roll2"
                               else (lambda i, b: (b, i ^ D1 ^ D2, 0))),
                  pl.BlockSpec((R, LANE), mown),
                  pl.BlockSpec((R, LANE), mat)],
        out_specs=pl.BlockSpec((1, R, LANE), own),
        out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
        interpret=interpret,
    )(x3, x3, x3, x3, mask_plane, mask_plane)


_PASS_FNS = {"local": _local_pass, "window": _window_pass,
             "wide_swap": _wide_pass, "wide_roll": _wide_pass,
             "wide_swap2": _wide2_pass, "wide_roll2": _wide2_pass}


def geometry(P: int, block_rows: int = DEFAULT_BLOCK_ROWS) -> Geometry:
    if P % LANE or P < MIN_P:
        raise ValueError(f"geometry needs P % {LANE} == 0 and P >= {MIN_P}")
    rows = P // LANE
    R = min(block_rows, rows)
    if R & (R - 1) or rows % R:
        raise ValueError("block_rows must be a power of two dividing rows")
    return Geometry(P=P, rows=rows, block_rows=R, grid=rows // R)


def halo_rows(dists) -> int:
    """Window-halo consumption of a stage run, in rows: a roll at
    distance d reads d/LANE rows below (a lane-distance stage's one-row
    carry costs a full row).  Single source of truth for the planner
    gate and the runtime guards."""
    return sum(max(d // LANE, 1) for d in dists)


def _flat_roll(x, d: int, nrows: int, interpret: bool):
    """Flat-order forward roll by ``d`` elements on a (nrows, 128) view
    (the roll branch of :func:`_apply_stage_in_block`, shared by the
    dist-plane passes)."""
    import jax
    import jax.numpy as jnp

    if d >= LANE:
        return _roll(x, d // LANE, 0, nrows, interpret)
    lr = _roll(x, d, 1, LANE, interpret)
    carry = _roll(lr, 1, 0, nrows, interpret)
    laneid = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(laneid < d, carry, lr)


def segscan_pass(x, dist_plane, dists: tuple, op: str, geom: Geometry):
    """Segmented Hillis-Steele scan: for each d in ``dists`` (ascending
    powers of two), ``x = comb(x, where(dist >= d, flat_roll(x, d),
    identity))``.  One HBM pass; masks derive from the static ``dist``
    plane in-kernel.  Valid while sum(dists) <= block elements (the
    window halo argument of :func:`plan_fused`; ``dist[p] >= d`` implies
    ``p >= d``, so wrapped sources are never selected)."""
    import jax.numpy as jnp

    if halo_rows(dists) > geom.block_rows:
        raise ValueError("scan stages exceed the window halo budget")
    interpret = _interpret()
    R = geom.block_rows

    comb = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}[op]
    # python literal (a traced scalar would be a captured constant,
    # which pallas_call rejects)
    if op == "sum":
        ident = 0
    elif jnp.issubdtype(x.dtype, jnp.integer):
        info = jnp.iinfo(x.dtype)
        ident = int(info.max if op == "min" else info.min)
    else:
        info = jnp.finfo(x.dtype)
        ident = float(info.max if op == "min" else info.min)

    def kern(xp_ref, xo_ref, dp_ref, do_ref, o_ref):
        w = jnp.concatenate([xp_ref[0], xo_ref[0]], axis=0)
        dv = jnp.concatenate([dp_ref[...], do_ref[...]], axis=0)
        for d in dists:
            taken = jnp.where(dv >= d, _flat_roll(w, d, 2 * R, interpret),
                              ident)
            w = comb(w, taken)
        o_ref[0] = w[R:]

    return _dist_window_call(kern, x, dist_plane, geom, interpret)


def fill_pass(x, dist_plane, dists: tuple, geom: Geometry):
    """Fill-forward: for each d=2^k in ``dists``, ``x = where(bit k of
    dist, flat_roll(x, d), x)`` — run heads copied over their runs in
    one HBM pass."""
    import jax.numpy as jnp

    if halo_rows(dists) > geom.block_rows:
        raise ValueError("fill stages exceed the window halo budget")
    interpret = _interpret()
    R = geom.block_rows

    def kern(xp_ref, xo_ref, dp_ref, do_ref, o_ref):
        w = jnp.concatenate([xp_ref[0], xo_ref[0]], axis=0)
        dv = jnp.concatenate([dp_ref[...], do_ref[...]], axis=0)
        for d in dists:
            bit = (dv & d) != 0
            w = jnp.where(bit, _flat_roll(w, d, 2 * R, interpret), w)
        o_ref[0] = w[R:]

    return _dist_window_call(kern, x, dist_plane, geom, interpret)


def _dist_window_call(kern, x, dist_plane, geom: Geometry, interpret: bool):
    """Leading batch dims share the dist plane (batch axis innermost so
    the pipeline reuses the resident dist block across lanes)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    R = geom.block_rows
    lead = x.shape[:-1]
    x3 = x.reshape(-1, geom.rows, LANE)
    d2 = dist_plane.reshape(geom.rows, LANE)
    prev = lambda i, b: (b, jnp.maximum(i - 1, 0), 0)
    own = lambda i, b: (b, i, 0)
    mprev = lambda i, _b: (jnp.maximum(i - 1, 0), 0)
    mown = lambda i, _b: (i, 0)
    out = pl.pallas_call(
        kern,
        grid=(geom.grid, x3.shape[0]),
        in_specs=[pl.BlockSpec((1, R, LANE), prev),
                  pl.BlockSpec((1, R, LANE), own),
                  pl.BlockSpec((R, LANE), mprev),
                  pl.BlockSpec((R, LANE), mown)],
        out_specs=pl.BlockSpec((1, R, LANE), own),
        out_shape=jax.ShapeDtypeStruct(x3.shape, x3.dtype),
        interpret=interpret,
    )(x3, x3, d2, d2)
    return out.reshape(*lead, geom.P)


def apply_fused(x, fused: FusedPlan, mask_planes):
    """Run every pass; drop-in equal to ``apply_stages(x, stage_plan)``
    over the last axis.  Leading batch dims share the mask planes (e.g.
    delivery moves all payload lanes through one network).
    ``mask_planes`` from :func:`device_mask_planes` (pytree-carried by
    the caller)."""
    interpret = _interpret()
    lead = x.shape[:-1]
    x3 = x.reshape(-1, fused.rows, LANE)
    for ps, plane in zip(fused.passes, mask_planes):
        x3 = _PASS_FNS[ps.kind](x3, plane, ps, fused, interpret)
    return x3.reshape(*lead, fused.P)
