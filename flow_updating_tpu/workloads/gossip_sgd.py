"""Gossip-SGD / decentralized FedAvg on the Flow-Updating substrate.

Each node ``i`` holds a parameter vector ``w_i`` (the node's *payload*,
``models/state.py`` vector mode) and a private dataset shard
(:mod:`flow_updating_tpu.workloads.data`).  One outer step is

1. **local compute** — ``local_steps`` full-batch gradient steps on the
   node's own loss, applied to the node's *input value*: the working
   model is the node's current Flow-Updating estimate
   ``w_i = value_i - sum(out flows_i)``, so shifting ``value_i`` by
   ``-lr * grad_i`` shifts the model by exactly that step while the
   ledgers keep conserving per-feature mass (Flow-Updating tracks
   dynamic inputs natively — no state reset on data change);
2. **communication** — ``comm_rounds`` Flow-Updating rounds: the
   gossip-averaging step, D features riding one message schedule;
3. optionally, every ``global_avg_every`` outer steps, **periodic global
   averaging** (Gossip-PGA, arXiv:2105.09080): every alive node's
   estimate is set to the exact alive-mean.  Implemented as the
   mass-preserving rebase ``value <- value - est + mean(est)`` — the sum
   of alive values is unchanged, so the knob composes with churn and the
   ledger invariants.

Node churn composes with training: killed nodes freeze (no local steps,
no firing), survivors keep averaging, and revived nodes re-join with
their ledgers intact — per-feature mass conservation is asserted by
:func:`per_feature_mass_residual` in the tests and the example.

The whole outer step is one jitted function of device state; the Python
loop only orchestrates churn and metrics sampling.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.rounds import (
    ChunkedState,
    chunk_count,
    chunked_node_estimates,
    chunked_rounds_per_visit,
    init_chunked_state,
    node_estimates,
    round_step,
    run_rounds_chunked,
    _chunk_major,
    _chunk_flat,
)
from flow_updating_tpu.models.state import FlowUpdatingState, init_state
from flow_updating_tpu.workloads.data import NodeDataset, pooled_loss


@dataclasses.dataclass(frozen=True)
class GossipSGDConfig:
    """Static trainer configuration (jit-static, like RoundConfig)."""

    lr: float = 0.2            # local gradient step size
    local_steps: int = 1       # gradient steps per outer step
    comm_rounds: int = 2       # Flow-Updating rounds per outer step
    outer_steps: int = 200
    global_avg_every: int = 0  # H of arXiv:2105.09080; 0 = pure gossip

    def __post_init__(self):
        if self.local_steps < 1:
            raise ValueError("local_steps must be >= 1")
        if self.comm_rounds < 0:
            raise ValueError("comm_rounds must be >= 0")
        if self.global_avg_every < 0:
            raise ValueError("global_avg_every must be >= 0 (0 = never)")


def per_feature_mass_residual(state: FlowUpdatingState, arrays) -> np.ndarray:
    """(D,) per-feature ``sum(est) - sum(value)`` — the vector-payload
    mass-conservation invariant (~0 at quiescence; transiently nonzero
    while messages are in flight or nodes are down)."""
    est = node_estimates(state, arrays)
    return np.asarray(jnp.sum(est, axis=0) - jnp.sum(state.value, axis=0))


def _grad(w, X, y, task: str):
    """Per-node full-batch gradient at per-node parameters ``w`` (N, D)."""
    z = jnp.einsum("nmd,nd->nm", X, w)
    if task == "linear":
        r = z - y
    else:
        r = jax.nn.sigmoid(z) - y
    return jnp.einsum("nmd,nm->nd", X, r) / X.shape[1]


def _global_average(state: FlowUpdatingState, arrays) -> FlowUpdatingState:
    """Exact global averaging over alive nodes (the PGA step): rebases
    every alive node's value so its estimate equals the alive-mean.
    ``sum_alive(value)`` is unchanged (the rebase swaps ``est`` terms for
    their mean, which sums to the same total), so mass conservation — and
    therefore the aggregate the ledgers track — survives the sync."""
    est = node_estimates(state, arrays)
    alive = state.alive
    a = alive[:, None]
    cnt = jnp.maximum(jnp.sum(alive), 1).astype(est.dtype)
    mean = jnp.sum(jnp.where(a, est, 0), axis=0) / cnt        # (D,)
    value = jnp.where(a, state.value - est + mean, state.value)
    return state.replace(value=value)


@functools.partial(
    jax.jit, static_argnames=("rcfg", "gcfg", "task", "do_global"))
def _outer_step(state, arrays, X, y, rcfg: RoundConfig,
                gcfg: GossipSGDConfig, task: str, do_global: bool):
    for _ in range(gcfg.local_steps):
        w = node_estimates(state, arrays)
        g = _grad(w, X, y, task)
        g = jnp.where(state.alive[:, None], g, 0)   # dead nodes freeze
        state = state.replace(
            value=state.value - jnp.asarray(gcfg.lr, w.dtype) * g)
    state = jax.lax.fori_loop(
        0, gcfg.comm_rounds, lambda _, s: round_step(s, arrays, rcfg), state)
    if do_global:
        state = _global_average(state, arrays)
    return state


@functools.partial(
    jax.jit,
    static_argnames=("rcfg", "gcfg", "task", "do_global", "mesh"))
def _outer_step_feature(state, arrays, X, y, rcfg: RoundConfig,
                        gcfg: GossipSGDConfig, task: str,
                        do_global: bool, mesh):
    """One outer step under feature-axis model parallelism: the WHOLE
    step — local gradients, comm rounds, optional PGA sync — runs inside
    one ``shard_map`` over the ``('nodes', 'feature')`` mesh, so the
    only cross-device traffic is (a) one ``psum('feature')`` per local
    step for the logits and (b) Gossip-PGA's ``psum('nodes')`` node-mean
    when the sync fires — no host round-trips, no GSPMD resharding
    between phases (parallel/feature.py)."""
    from jax.sharding import PartitionSpec as P

    from flow_updating_tpu.parallel import feature as _F
    from flow_updating_tpu.parallel.mesh import NODE_AXIS, shard_map

    specs = _F.state_feature_specs(state)
    aspec = jax.tree.map(lambda _: P(), arrays)
    xspec = P(None, None, _F.FEATURE_AXIS)
    node_axis = (NODE_AXIS in mesh.axis_names
                 and int(mesh.shape[NODE_AXIS]) > 1)

    def body(st, ta, Xs, ys):
        for _ in range(gcfg.local_steps):
            w = node_estimates(st, ta)
            z = _F.feature_logits(Xs, w)          # psum over 'feature'
            r = (z - ys) if task == "linear" else (jax.nn.sigmoid(z) - ys)
            g = jnp.einsum("nmd,nm->nd", Xs, r) / Xs.shape[1]
            g = jnp.where(st.alive[:, None], g, 0)
            st = st.replace(
                value=st.value - jnp.asarray(gcfg.lr, w.dtype) * g)
        st = jax.lax.fori_loop(
            0, gcfg.comm_rounds, lambda _, s: round_step(s, ta, rcfg), st)
        if do_global:
            st = _F._pga_rebase(st, ta, node_axis)  # psum over 'nodes'
        return st

    fn = shard_map(body, mesh=mesh, in_specs=(specs, aspec, xspec, P()),
                   out_specs=specs, check_vma=False)
    return fn(state, arrays, X, y)


@functools.partial(
    jax.jit,
    static_argnames=("rcfg", "gcfg", "task", "do_global", "rpv", "mesh"))
def _outer_step_chunked_feature(cs: ChunkedState, arrays, X, y,
                                rcfg: RoundConfig, gcfg: GossipSGDConfig,
                                task: str, do_global: bool, rpv: int,
                                mesh):
    """Chunked schedule x feature sharding: the comm phase streams each
    device's OWN chunks through the explicit shard_map path
    (parallel/feature.run_chunked_feature — per-device wire is E*c lanes
    per visit); local compute and the PGA rebase run as sharded-array
    ops (the chunk axis is the partitioned dimension, so the gradient's
    cross-chunk reads resolve to the feature-axis collectives GSPMD
    inserts — one gather per local step, outside the round scan)."""
    from flow_updating_tpu.parallel import feature as _F

    for _ in range(gcfg.local_steps):
        w = chunked_node_estimates(cs, arrays)
        g = _grad(w, X, y, task)
        g = jnp.where(cs.state.alive[:, None], g, 0)
        lr = jnp.asarray(gcfg.lr, w.dtype)
        cs = cs.replace(value=cs.value - _chunk_major(lr * g, cs.n_chunks))
    if gcfg.comm_rounds:
        sf = int(mesh.shape[_F.FEATURE_AXIS])
        cs = _F.run_chunked_feature(
            cs, arrays, rcfg,
            num_rounds=(cs.n_chunks // sf) * gcfg.comm_rounds,
            mesh=mesh, rounds_per_visit=rpv)
    if do_global:
        cs = _global_average_chunked(cs, arrays)
    return cs


def _global_average_chunked(cs: ChunkedState, arrays) -> ChunkedState:
    """The PGA rebase on chunk-major state: identical math to
    :func:`_global_average`, applied per contiguous feature block."""
    est = chunked_node_estimates(cs, arrays)          # (N, D)
    alive = cs.state.alive
    a = alive[:, None]
    cnt = jnp.maximum(jnp.sum(alive), 1).astype(est.dtype)
    mean = jnp.sum(jnp.where(a, est, 0), axis=0) / cnt
    value = _chunk_flat(cs.value)
    value = jnp.where(a, value - est + mean, value)
    return cs.replace(value=_chunk_major(value, cs.n_chunks))


@functools.partial(
    jax.jit,
    static_argnames=("rcfg", "gcfg", "task", "do_global", "rpv"))
def _outer_step_chunked(cs: ChunkedState, arrays, X, y, rcfg: RoundConfig,
                        gcfg: GossipSGDConfig, task: str, do_global: bool,
                        rpv: int):
    """One outer step over the pipelined chunked schedule: local compute
    touches the chunk-major values directly; the comm phase advances
    EVERY chunk's instance by ``gcfg.comm_rounds`` rounds
    (``comm_rounds / rpv`` full passes)."""
    for _ in range(gcfg.local_steps):
        w = chunked_node_estimates(cs, arrays)
        g = _grad(w, X, y, task)
        g = jnp.where(cs.state.alive[:, None], g, 0)
        lr = jnp.asarray(gcfg.lr, w.dtype)
        cs = cs.replace(value=cs.value - _chunk_major(lr * g, cs.n_chunks))
    if gcfg.comm_rounds:
        cs = run_rounds_chunked(
            cs, arrays, rcfg,
            num_rounds=cs.n_chunks * gcfg.comm_rounds,
            rounds_per_visit=rpv)
    if do_global:
        cs = _global_average_chunked(cs, arrays)
    return cs


@functools.partial(jax.jit, static_argnames=("rcfg", "gcfg", "task"))
def _grid_step(states, arrays, X, y, H, k, rcfg: RoundConfig,
               gcfg: GossipSGDConfig, task: str):
    """One vmapped outer step over B trainer lanes sharing ONE topology
    shape (the sweep discipline): per-lane datasets (the non-IID axis)
    and per-lane PGA periods ``H`` (TRACED int32, so every period in the
    grid rides the same compiled program — 0 means never)."""

    def one(st, Xs, ys, h):
        for _ in range(gcfg.local_steps):
            w = node_estimates(st, arrays)
            g = _grad(w, Xs, ys, task)
            g = jnp.where(st.alive[:, None], g, 0)
            st = st.replace(
                value=st.value - jnp.asarray(gcfg.lr, w.dtype) * g)
        st = jax.lax.fori_loop(
            0, gcfg.comm_rounds,
            lambda _, s: round_step(s, arrays, rcfg), st)
        do = (h > 0) & (((k + 1) % jnp.maximum(h, 1)) == 0)
        ga = _global_average(st, arrays)
        return st.replace(value=jnp.where(do, ga.value, st.value))

    return jax.vmap(one, in_axes=(0, 0, 0, 0))(states, X, y, H)


def train_grid(topo, datasets, periods, cfg: GossipSGDConfig,
               round_cfg: RoundConfig | None = None,
               w0: np.ndarray | None = None) -> list[dict]:
    """The DFL sweep: a (non-IID shard) x (PGA period) grid trained as
    ONE vmapped program — ``B = len(datasets) * len(periods)`` lanes,
    one compile for the whole grid (same-shape topologies share the jit
    cache entry across calls, the sweep engine's shape-bucket
    discipline; build ``datasets`` with ``make_dataset(dirichlet_alpha=
    ...)`` for the Dirichlet non-IID axis).

    Returns one report dict per lane (row-major over datasets x
    periods), each tagged with its lane coordinates."""
    if round_cfg is None:
        round_cfg = RoundConfig.fast(dtype="float64")
    if round_cfg.kernel != "edge":
        raise ValueError("train_grid drives the edge kernel "
                         "(kernel='edge')")
    tasks = {d.task for d in datasets}
    feats = {d.features for d in datasets}
    if len(tasks) != 1 or len(feats) != 1:
        raise ValueError("grid datasets must share task and feature "
                         f"count (got tasks={tasks}, D={feats})")
    arrays = topo.device_arrays(
        coloring=round_cfg.needs_coloring,
        segment_ell=round_cfg.use_segment_ell,
        segment_benes=round_cfg.segment_benes_mode,
        delivery_benes=round_cfg.delivery_benes_mode)
    dt = round_cfg.jnp_dtype
    D = feats.pop()
    task = tasks.pop()
    if w0 is None:
        w0 = np.zeros((topo.num_nodes, D))
    lanes = [(d, h) for d in datasets for h in periods]
    states = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[init_state(topo, round_cfg, values=w0) for _ in lanes])
    X = jnp.stack([jnp.asarray(d.X, dt) for d, _ in lanes])
    y = jnp.stack([jnp.asarray(d.y, dt) for d, _ in lanes])
    H = jnp.asarray([h for _, h in lanes], jnp.int32)
    for k in range(cfg.outer_steps):
        states = _grid_step(states, arrays, X, y, H,
                            jnp.asarray(k, jnp.int32), round_cfg, cfg,
                            task)
    reports = []
    for i, (d, h) in enumerate(lanes):
        st = jax.tree.map(lambda x, i=i: x[i], states)
        w = np.asarray(node_estimates(st, arrays))
        alive = np.asarray(st.alive)
        w_mean = w[alive].mean(axis=0) if alive.any() else w.mean(axis=0)
        res = np.asarray(jnp.sum(node_estimates(st, arrays), axis=0)
                         - jnp.sum(st.value, axis=0))
        wa = w[alive] if alive.any() else w
        reports.append({
            "lane": i,
            "global_avg_every": int(h),
            "outer_steps": cfg.outer_steps,
            "pooled_loss": pooled_loss(d, w_mean),
            "consensus_dispersion": (
                float(np.abs(wa - wa.mean(axis=0)).max()) if len(wa)
                else 0.0),
            "max_mass_residual": float(np.abs(res).max()),
        })
    return reports


class GossipSGDTrainer:
    """Decentralized gossip-SGD over one topology + dataset.

    ``round_cfg`` defaults to the fast synchronous collect-all dynamics
    in float64 (every node averages with all neighbors each comm round);
    any edge-kernel :class:`RoundConfig` works — e.g.
    ``RoundConfig.reference()`` trains over the faithful asynchronous
    message dynamics, and ``RoundConfig.fast('pairwise')`` over
    edge-colored matching gossip.
    """

    def __init__(self, topo, data: NodeDataset,
                 cfg: GossipSGDConfig | None = None,
                 round_cfg: RoundConfig | None = None,
                 w0: np.ndarray | None = None,
                 chunk: int = 0,
                 feature_shards: int = 0,
                 rounds_per_visit: int | None = None):
        if data.num_nodes != topo.num_nodes:
            raise ValueError(
                f"dataset covers {data.num_nodes} nodes, topology has "
                f"{topo.num_nodes}")
        if round_cfg is None:
            round_cfg = RoundConfig.fast(dtype="float64")
        if round_cfg.kernel != "edge":
            raise ValueError(
                "gossip-SGD mutates per-node values between comm rounds; "
                "it drives the edge kernel (kernel='edge')")
        self.topo = topo
        self.data = data
        cfg = cfg if cfg is not None else GossipSGDConfig()
        self.cfg = cfg
        self.round_cfg = round_cfg
        self.arrays = topo.device_arrays(
            coloring=round_cfg.needs_coloring,
            segment_ell=round_cfg.use_segment_ell,
            segment_benes=round_cfg.segment_benes_mode,
            delivery_benes=round_cfg.delivery_benes_mode,
        )
        dt = round_cfg.jnp_dtype
        if w0 is None:
            w0 = np.zeros((topo.num_nodes, data.features))

        # -- model-scale axes (docs/WORKLOADS.md "model scale") ----------
        self.cstate = None
        self._state = None
        self.chunk = int(chunk)
        self.feature_shards = int(feature_shards)
        self._mesh = None
        if self.chunk:
            chunk_count(data.features, self.chunk)  # divisibility
            self._rpv = (int(rounds_per_visit) if rounds_per_visit
                         else chunked_rounds_per_visit(self.arrays,
                                                       round_cfg))
            if cfg.comm_rounds % max(self._rpv, 1):
                raise ValueError(
                    f"comm_rounds={cfg.comm_rounds} must be a multiple "
                    f"of rounds_per_visit={self._rpv} (whole chunk "
                    "passes per outer step)")
        else:
            self._rpv = None
            if rounds_per_visit:
                raise ValueError("rounds_per_visit is a chunked-schedule "
                                 "knob; pass chunk=c to enable it")
        if self.feature_shards:
            from flow_updating_tpu.parallel import feature as _F

            self._mesh = _F.feature_mesh(self.feature_shards)
            if self.chunk:
                n = data.features // self.chunk
                if n % self.feature_shards:
                    raise ValueError(
                        f"n_chunks={n} must divide evenly over "
                        f"{self.feature_shards} feature shards")
            elif data.features % self.feature_shards:
                raise ValueError(
                    f"features D={data.features} must divide evenly "
                    f"over {self.feature_shards} feature shards")
        if self.chunk:
            self.cstate = init_chunked_state(topo, round_cfg, self.chunk,
                                             w0)
            if self._mesh is not None:
                from flow_updating_tpu.parallel import feature as _F

                specs = _F.chunked_feature_specs(self.cstate)
                self.cstate = jax.tree.map(
                    lambda x, s: jax.device_put(
                        x, jax.sharding.NamedSharding(self._mesh, s)),
                    self.cstate, specs)
        else:
            self.state = init_state(topo, round_cfg, values=w0)
            if self._mesh is not None:
                from flow_updating_tpu.parallel import feature as _F

                self.state = _F.place_feature_state(self.state,
                                                    self._mesh)
        self._X = jnp.asarray(data.X, dt)
        self._y = jnp.asarray(data.y, dt)
        if self._mesh is not None and not self.chunk:
            from flow_updating_tpu.parallel.mesh import FEATURE_AXIS
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._X = jax.device_put(self._X, NamedSharding(
                self._mesh, P(None, None, FEATURE_AXIS)))
        self.outer_done = 0

    # -- payload views ---------------------------------------------------
    def params(self) -> np.ndarray:
        """(N, D) current per-node models (the Flow-Updating estimates)."""
        if self.cstate is not None:
            return np.asarray(chunked_node_estimates(self.cstate,
                                                     self.arrays))
        return np.asarray(node_estimates(self.state, self.arrays))

    @property
    def state(self) -> FlowUpdatingState:
        """The protocol state.  In chunked mode the state of record
        lives in ``cstate`` (chunk-major leaves + shared churn masks);
        reading ``.state`` always reflects it and assigning through
        ``.state`` updates the chunked window, so the long-standing
        attribute can never go stale behind ``cstate`` mutations."""
        return self.cstate.state if self.cstate is not None else self._state

    @state.setter
    def state(self, value: FlowUpdatingState) -> None:
        if self.cstate is not None:
            self.cstate = self.cstate.replace(state=value)
        else:
            self._state = value

    @property
    def control(self) -> FlowUpdatingState:
        """The control-plane state (liveness, round counter) — an alias
        of :attr:`state` (which tracks ``cstate`` in chunked mode)."""
        return self.state

    def consensus_dispersion(self) -> float:
        """max_i ||w_i - mean(w)||_inf over alive nodes."""
        w = self.params()
        alive = np.asarray(self.control.alive)
        wa = w[alive]
        return float(np.abs(wa - wa.mean(axis=0)).max()) if len(wa) else 0.0

    def distance_to_centralized(self, w_opt) -> float:
        """Max over ALIVE nodes of the relative L2 distance to the
        centralized solution ``w_opt`` — THE definition of the workload's
        acceptance metric, owned here so every driver (CLI, example,
        tests) reports the same thing.  Dead nodes are excluded: their
        params froze at death and don't represent the survivors."""
        w_opt = np.asarray(w_opt)
        alive = np.asarray(self.control.alive)
        w = self.params()
        if alive.any():
            w = w[alive]
        denom = max(float(np.linalg.norm(w_opt)), 1e-12)
        return float(np.linalg.norm(w - w_opt, axis=1).max() / denom)

    def mass_residual(self) -> np.ndarray:
        if self.cstate is not None:
            est = chunked_node_estimates(self.cstate, self.arrays)
            value = _chunk_flat(self.cstate.value)
            return np.asarray(jnp.sum(est, axis=0)
                              - jnp.sum(value, axis=0))
        return per_feature_mass_residual(self.state, self.arrays)

    # -- fault injection -------------------------------------------------
    # churn is the service membership primitive (ONE implementation for
    # the trainer's schedule, the Engine's fault injection and the
    # streaming service's suspend/resume — service/membership.py)
    def kill_nodes(self, nodes) -> None:
        from flow_updating_tpu.service import membership

        # the .state property routes the edit into cstate in chunked mode
        self.state = membership.set_alive(self.state, nodes, False)

    def revive_nodes(self, nodes) -> None:
        from flow_updating_tpu.service import membership

        self.state = membership.set_alive(self.state, nodes, True)

    # -- training --------------------------------------------------------
    def step(self) -> None:
        """One outer step (local compute + gossip + optional PGA sync)."""
        H = self.cfg.global_avg_every
        do_global = bool(H) and (self.outer_done + 1) % H == 0
        if self.cstate is not None:
            step_fn = _outer_step_chunked
            extra = ()
            if self._mesh is not None:
                step_fn, extra = _outer_step_chunked_feature, (self._mesh,)
            self.cstate = step_fn(
                self.cstate, self.arrays, self._X, self._y,
                self.round_cfg, self.cfg, self.data.task, do_global,
                self._rpv, *extra)
        elif self._mesh is not None:
            self.state = _outer_step_feature(
                self.state, self.arrays, self._X, self._y,
                self.round_cfg, self.cfg, self.data.task, do_global,
                self._mesh)
        else:
            self.state = _outer_step(
                self.state, self.arrays, self._X, self._y, self.round_cfg,
                self.cfg, self.data.task, do_global)
        self.outer_done += 1

    def train(self, churn: dict | None = None, sample_every: int = 0,
              callback=None) -> dict:
        """Run ``cfg.outer_steps`` outer steps.

        ``churn`` maps an outer-step index to ``("kill", ids)`` /
        ``("revive", ids)``, applied before that step — mid-training node
        churn.  ``sample_every`` > 0 invokes ``callback(step, trainer)``
        on that cadence.  Returns the final report (see
        :meth:`report`)."""
        churn = churn or {}
        for k in range(self.cfg.outer_steps):
            if k in churn:
                verb, ids = churn[k]
                {"kill": self.kill_nodes, "revive": self.revive_nodes}[verb](
                    ids)
            self.step()
            if sample_every and callback and (k + 1) % sample_every == 0:
                callback(k + 1, self)
        return self.report()

    def report(self) -> dict:
        w = self.params()
        alive = np.asarray(self.control.alive)
        w_mean = w[alive].mean(axis=0) if alive.any() else w.mean(axis=0)
        res = self.mass_residual()
        return {
            "outer_steps": self.outer_done,
            "comm_rounds_total": self.outer_done * self.cfg.comm_rounds,
            "task": self.data.task,
            "features": self.data.features,
            "nodes": self.topo.num_nodes,
            "alive": int(alive.sum()),
            "pooled_loss": pooled_loss(self.data, w_mean),
            "consensus_dispersion": self.consensus_dispersion(),
            "max_mass_residual": float(np.abs(res).max()),
            "chunk": self.chunk or None,
            "rounds_per_visit": self._rpv,
            "feature_shards": self.feature_shards or None,
            "comm_bytes_total": self.comm_bytes_total(),
        }

    def comm_bytes_total(self) -> int:
        """Total payload bytes the comm phases have moved over edges so
        far — the x-axis of the convergence-vs-bytes methodology
        (arXiv:2506.10607).  Every schedule moves the same bytes per
        underlying round x lane: chunking/sharding change WHO moves them
        and how many per device, not the total."""
        from flow_updating_tpu.obs.profile import payload_bytes_per_round

        per = payload_bytes_per_round(
            self.topo.num_edges, self.data.features,
            chunk=self.chunk or None,
            dtype_bytes=jnp.dtype(self.round_cfg.jnp_dtype).itemsize)
        rounds_per_outer = self.cfg.comm_rounds * (
            1 if not self.chunk else self.data.features // self.chunk)
        return int(self.outer_done * rounds_per_outer
                   * per["bytes_per_round"])
