"""Gossip-SGD / decentralized FedAvg on the Flow-Updating substrate.

Each node ``i`` holds a parameter vector ``w_i`` (the node's *payload*,
``models/state.py`` vector mode) and a private dataset shard
(:mod:`flow_updating_tpu.workloads.data`).  One outer step is

1. **local compute** — ``local_steps`` full-batch gradient steps on the
   node's own loss, applied to the node's *input value*: the working
   model is the node's current Flow-Updating estimate
   ``w_i = value_i - sum(out flows_i)``, so shifting ``value_i`` by
   ``-lr * grad_i`` shifts the model by exactly that step while the
   ledgers keep conserving per-feature mass (Flow-Updating tracks
   dynamic inputs natively — no state reset on data change);
2. **communication** — ``comm_rounds`` Flow-Updating rounds: the
   gossip-averaging step, D features riding one message schedule;
3. optionally, every ``global_avg_every`` outer steps, **periodic global
   averaging** (Gossip-PGA, arXiv:2105.09080): every alive node's
   estimate is set to the exact alive-mean.  Implemented as the
   mass-preserving rebase ``value <- value - est + mean(est)`` — the sum
   of alive values is unchanged, so the knob composes with churn and the
   ledger invariants.

Node churn composes with training: killed nodes freeze (no local steps,
no firing), survivors keep averaging, and revived nodes re-join with
their ledgers intact — per-feature mass conservation is asserted by
:func:`per_feature_mass_residual` in the tests and the example.

The whole outer step is one jitted function of device state; the Python
loop only orchestrates churn and metrics sampling.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.rounds import node_estimates, round_step
from flow_updating_tpu.models.state import FlowUpdatingState, init_state
from flow_updating_tpu.workloads.data import NodeDataset, pooled_loss


@dataclasses.dataclass(frozen=True)
class GossipSGDConfig:
    """Static trainer configuration (jit-static, like RoundConfig)."""

    lr: float = 0.2            # local gradient step size
    local_steps: int = 1       # gradient steps per outer step
    comm_rounds: int = 2       # Flow-Updating rounds per outer step
    outer_steps: int = 200
    global_avg_every: int = 0  # H of arXiv:2105.09080; 0 = pure gossip

    def __post_init__(self):
        if self.local_steps < 1:
            raise ValueError("local_steps must be >= 1")
        if self.comm_rounds < 0:
            raise ValueError("comm_rounds must be >= 0")
        if self.global_avg_every < 0:
            raise ValueError("global_avg_every must be >= 0 (0 = never)")


def per_feature_mass_residual(state: FlowUpdatingState, arrays) -> np.ndarray:
    """(D,) per-feature ``sum(est) - sum(value)`` — the vector-payload
    mass-conservation invariant (~0 at quiescence; transiently nonzero
    while messages are in flight or nodes are down)."""
    est = node_estimates(state, arrays)
    return np.asarray(jnp.sum(est, axis=0) - jnp.sum(state.value, axis=0))


def _grad(w, X, y, task: str):
    """Per-node full-batch gradient at per-node parameters ``w`` (N, D)."""
    z = jnp.einsum("nmd,nd->nm", X, w)
    if task == "linear":
        r = z - y
    else:
        r = jax.nn.sigmoid(z) - y
    return jnp.einsum("nmd,nm->nd", X, r) / X.shape[1]


def _global_average(state: FlowUpdatingState, arrays) -> FlowUpdatingState:
    """Exact global averaging over alive nodes (the PGA step): rebases
    every alive node's value so its estimate equals the alive-mean.
    ``sum_alive(value)`` is unchanged (the rebase swaps ``est`` terms for
    their mean, which sums to the same total), so mass conservation — and
    therefore the aggregate the ledgers track — survives the sync."""
    est = node_estimates(state, arrays)
    alive = state.alive
    a = alive[:, None]
    cnt = jnp.maximum(jnp.sum(alive), 1).astype(est.dtype)
    mean = jnp.sum(jnp.where(a, est, 0), axis=0) / cnt        # (D,)
    value = jnp.where(a, state.value - est + mean, state.value)
    return state.replace(value=value)


@functools.partial(
    jax.jit, static_argnames=("rcfg", "gcfg", "task", "do_global"))
def _outer_step(state, arrays, X, y, rcfg: RoundConfig,
                gcfg: GossipSGDConfig, task: str, do_global: bool):
    for _ in range(gcfg.local_steps):
        w = node_estimates(state, arrays)
        g = _grad(w, X, y, task)
        g = jnp.where(state.alive[:, None], g, 0)   # dead nodes freeze
        state = state.replace(
            value=state.value - jnp.asarray(gcfg.lr, w.dtype) * g)
    state = jax.lax.fori_loop(
        0, gcfg.comm_rounds, lambda _, s: round_step(s, arrays, rcfg), state)
    if do_global:
        state = _global_average(state, arrays)
    return state


class GossipSGDTrainer:
    """Decentralized gossip-SGD over one topology + dataset.

    ``round_cfg`` defaults to the fast synchronous collect-all dynamics
    in float64 (every node averages with all neighbors each comm round);
    any edge-kernel :class:`RoundConfig` works — e.g.
    ``RoundConfig.reference()`` trains over the faithful asynchronous
    message dynamics, and ``RoundConfig.fast('pairwise')`` over
    edge-colored matching gossip.
    """

    def __init__(self, topo, data: NodeDataset,
                 cfg: GossipSGDConfig = GossipSGDConfig(),
                 round_cfg: RoundConfig | None = None,
                 w0: np.ndarray | None = None):
        if data.num_nodes != topo.num_nodes:
            raise ValueError(
                f"dataset covers {data.num_nodes} nodes, topology has "
                f"{topo.num_nodes}")
        if round_cfg is None:
            round_cfg = RoundConfig.fast(dtype="float64")
        if round_cfg.kernel != "edge":
            raise ValueError(
                "gossip-SGD mutates per-node values between comm rounds; "
                "it drives the edge kernel (kernel='edge')")
        self.topo = topo
        self.data = data
        self.cfg = cfg
        self.round_cfg = round_cfg
        self.arrays = topo.device_arrays(
            coloring=round_cfg.needs_coloring,
            segment_ell=round_cfg.use_segment_ell,
            segment_benes=round_cfg.segment_benes_mode,
            delivery_benes=round_cfg.delivery_benes_mode,
        )
        dt = round_cfg.jnp_dtype
        if w0 is None:
            w0 = np.zeros((topo.num_nodes, data.features))
        self.state = init_state(topo, round_cfg, values=w0)
        self._X = jnp.asarray(data.X, dt)
        self._y = jnp.asarray(data.y, dt)
        self.outer_done = 0

    # -- payload views ---------------------------------------------------
    def params(self) -> np.ndarray:
        """(N, D) current per-node models (the Flow-Updating estimates)."""
        return np.asarray(node_estimates(self.state, self.arrays))

    def consensus_dispersion(self) -> float:
        """max_i ||w_i - mean(w)||_inf over alive nodes."""
        w = self.params()
        alive = np.asarray(self.state.alive)
        wa = w[alive]
        return float(np.abs(wa - wa.mean(axis=0)).max()) if len(wa) else 0.0

    def distance_to_centralized(self, w_opt) -> float:
        """Max over ALIVE nodes of the relative L2 distance to the
        centralized solution ``w_opt`` — THE definition of the workload's
        acceptance metric, owned here so every driver (CLI, example,
        tests) reports the same thing.  Dead nodes are excluded: their
        params froze at death and don't represent the survivors."""
        w_opt = np.asarray(w_opt)
        alive = np.asarray(self.state.alive)
        w = self.params()
        if alive.any():
            w = w[alive]
        denom = max(float(np.linalg.norm(w_opt)), 1e-12)
        return float(np.linalg.norm(w - w_opt, axis=1).max() / denom)

    def mass_residual(self) -> np.ndarray:
        return per_feature_mass_residual(self.state, self.arrays)

    # -- fault injection -------------------------------------------------
    # churn is the service membership primitive (ONE implementation for
    # the trainer's schedule, the Engine's fault injection and the
    # streaming service's suspend/resume — service/membership.py)
    def kill_nodes(self, nodes) -> None:
        from flow_updating_tpu.service import membership

        self.state = membership.set_alive(self.state, nodes, False)

    def revive_nodes(self, nodes) -> None:
        from flow_updating_tpu.service import membership

        self.state = membership.set_alive(self.state, nodes, True)

    # -- training --------------------------------------------------------
    def step(self) -> None:
        """One outer step (local compute + gossip + optional PGA sync)."""
        H = self.cfg.global_avg_every
        do_global = bool(H) and (self.outer_done + 1) % H == 0
        self.state = _outer_step(
            self.state, self.arrays, self._X, self._y, self.round_cfg,
            self.cfg, self.data.task, do_global)
        self.outer_done += 1

    def train(self, churn: dict | None = None, sample_every: int = 0,
              callback=None) -> dict:
        """Run ``cfg.outer_steps`` outer steps.

        ``churn`` maps an outer-step index to ``("kill", ids)`` /
        ``("revive", ids)``, applied before that step — mid-training node
        churn.  ``sample_every`` > 0 invokes ``callback(step, trainer)``
        on that cadence.  Returns the final report (see
        :meth:`report`)."""
        churn = churn or {}
        for k in range(self.cfg.outer_steps):
            if k in churn:
                verb, ids = churn[k]
                {"kill": self.kill_nodes, "revive": self.revive_nodes}[verb](
                    ids)
            self.step()
            if sample_every and callback and (k + 1) % sample_every == 0:
                callback(k + 1, self)
        return self.report()

    def report(self) -> dict:
        w = self.params()
        alive = np.asarray(self.state.alive)
        w_mean = w[alive].mean(axis=0) if alive.any() else w.mean(axis=0)
        res = self.mass_residual()
        return {
            "outer_steps": self.outer_done,
            "comm_rounds_total": self.outer_done * self.cfg.comm_rounds,
            "task": self.data.task,
            "features": self.data.features,
            "nodes": self.topo.num_nodes,
            "alive": int(alive.sum()),
            "pooled_loss": pooled_loss(self.data, w_mean),
            "consensus_dispersion": self.consensus_dispersion(),
            "max_mass_residual": float(np.abs(res).max()),
        }
