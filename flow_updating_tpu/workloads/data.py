"""Synthetic per-node datasets for the decentralized-learning workloads.

Each node owns a private shard of a global regression/classification
problem — the federated-learning data model (arXiv:2506.10607 §II): one
ground-truth parameter vector generates every node's labels, per-node
feature distributions may be shifted (``heterogeneity``, the non-IID
knob), and the *centralized* solution on the pooled data is the reference
the decentralized run must agree with (the gossip-SGD acceptance bar).
"""

from __future__ import annotations

import dataclasses

import numpy as np

TASKS = ("linear", "logistic")


@dataclasses.dataclass(frozen=True)
class NodeDataset:
    """Per-node supervised data: ``X`` (N, m, D), ``y`` (N, m)."""

    X: np.ndarray
    y: np.ndarray
    task: str
    w_true: np.ndarray  # (D,) generating parameters

    @property
    def num_nodes(self) -> int:
        return self.X.shape[0]

    @property
    def features(self) -> int:
        return self.X.shape[2]


def make_dataset(
    num_nodes: int,
    features: int,
    samples_per_node: int = 16,
    task: str = "linear",
    noise: float = 0.1,
    heterogeneity: float = 0.0,
    dirichlet_alpha: float | None = None,
    dirichlet_components: int = 8,
    seed: int = 0,
) -> NodeDataset:
    """One global problem, sharded across nodes.

    Two non-IID knobs, composable:

    * ``heterogeneity`` > 0 shifts each node's feature distribution by a
      node-specific mean of that magnitude; 0 = IID.
    * ``dirichlet_alpha`` is the standard federated Dirichlet shard
      synthesis (the non-IID axis of the DFL sweeps, arXiv:2506.10607
      §II): ``dirichlet_components`` latent feature clusters with
      distinct means, and node ``n`` draws each sample's cluster from
      its own mixture ``pi_n ~ Dir(alpha * 1_K)``.  Small ``alpha``
      concentrates every node on a few clusters (strongly non-IID);
      ``alpha -> inf`` recovers the uniform mixture.  Fully determined
      by ``seed`` (one `default_rng` stream).
    """
    if task not in TASKS:
        raise ValueError(f"unknown task {task!r} (have {TASKS})")
    if dirichlet_alpha is not None and dirichlet_alpha <= 0:
        raise ValueError(
            f"dirichlet_alpha must be > 0 (got {dirichlet_alpha}); "
            "omit it (None) for IID shards")
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=features) / np.sqrt(features)
    shift = heterogeneity * rng.normal(size=(num_nodes, 1, features))
    X = rng.normal(size=(num_nodes, samples_per_node, features)) + shift
    if dirichlet_alpha is not None:
        K = int(dirichlet_components)
        if K < 2:
            raise ValueError("dirichlet_components must be >= 2")
        # latent cluster means on the unit-ish sphere; pi_n ~ Dir(alpha)
        # per node; each sample joins cluster c_nm ~ Cat(pi_n) and is
        # shifted by that cluster's mean
        centers = rng.normal(size=(K, features)) / np.sqrt(features)
        pi = rng.dirichlet(dirichlet_alpha * np.ones(K), size=num_nodes)
        cdf = np.cumsum(pi, axis=1)
        cdf[:, -1] = 1.0   # float cumsum can land at 1 - eps; a uniform
        #                    draw above it would index past cluster K-1
        u = rng.uniform(size=(num_nodes, samples_per_node, 1))
        comp = (u > cdf[:, None, :]).sum(axis=2)  # (N, m) cluster ids
        X = X + centers[comp]
    logits = np.einsum("nmd,d->nm", X, w_true)
    if task == "linear":
        y = logits + noise * rng.normal(size=logits.shape)
    else:
        p = 1.0 / (1.0 + np.exp(-logits / max(noise, 1e-12)))
        y = (rng.uniform(size=logits.shape) < p).astype(np.float64)
    return NodeDataset(X=X, y=y, task=task, w_true=w_true)


def pooled_loss(ds: NodeDataset, w: np.ndarray) -> float:
    """Centralized objective at ``w``: mean over ALL samples of the
    per-sample loss (the average of the per-node objectives — every node
    holds the same number of samples)."""
    X = ds.X.reshape(-1, ds.features)
    y = ds.y.reshape(-1)
    z = X @ w
    if ds.task == "linear":
        return float(0.5 * np.mean((z - y) ** 2))
    # logistic negative log-likelihood, numerically stable
    return float(np.mean(np.logaddexp(0.0, z) - y * z))


def centralized_solution(
    ds: NodeDataset, gd_steps: int = 4000, gd_lr: float = 0.5
) -> np.ndarray:
    """Minimizer of the pooled objective — closed form for least squares,
    full-batch gradient descent for logistic regression."""
    X = ds.X.reshape(-1, ds.features)
    y = ds.y.reshape(-1)
    if ds.task == "linear":
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        return w
    w = np.zeros(ds.features)
    m = len(y)
    # lr scaled by the logistic Hessian bound ||X||^2 / (4m)
    L = 0.25 * np.linalg.norm(X, 2) ** 2 / m
    lr = gd_lr / max(L, 1e-12)
    for _ in range(gd_steps):
        g = X.T @ (1.0 / (1.0 + np.exp(-(X @ w))) - y) / m
        w = w - lr * g
        if np.linalg.norm(g) < 1e-12:
            break
    return w
