"""Synthetic per-node datasets for the decentralized-learning workloads.

Each node owns a private shard of a global regression/classification
problem — the federated-learning data model (arXiv:2506.10607 §II): one
ground-truth parameter vector generates every node's labels, per-node
feature distributions may be shifted (``heterogeneity``, the non-IID
knob), and the *centralized* solution on the pooled data is the reference
the decentralized run must agree with (the gossip-SGD acceptance bar).
"""

from __future__ import annotations

import dataclasses

import numpy as np

TASKS = ("linear", "logistic")


@dataclasses.dataclass(frozen=True)
class NodeDataset:
    """Per-node supervised data: ``X`` (N, m, D), ``y`` (N, m)."""

    X: np.ndarray
    y: np.ndarray
    task: str
    w_true: np.ndarray  # (D,) generating parameters

    @property
    def num_nodes(self) -> int:
        return self.X.shape[0]

    @property
    def features(self) -> int:
        return self.X.shape[2]


def make_dataset(
    num_nodes: int,
    features: int,
    samples_per_node: int = 16,
    task: str = "linear",
    noise: float = 0.1,
    heterogeneity: float = 0.0,
    seed: int = 0,
) -> NodeDataset:
    """One global problem, sharded across nodes.

    ``heterogeneity`` > 0 shifts each node's feature distribution by a
    node-specific mean of that magnitude (non-IID shards); 0 = IID.
    """
    if task not in TASKS:
        raise ValueError(f"unknown task {task!r} (have {TASKS})")
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=features) / np.sqrt(features)
    shift = heterogeneity * rng.normal(size=(num_nodes, 1, features))
    X = rng.normal(size=(num_nodes, samples_per_node, features)) + shift
    logits = np.einsum("nmd,d->nm", X, w_true)
    if task == "linear":
        y = logits + noise * rng.normal(size=logits.shape)
    else:
        p = 1.0 / (1.0 + np.exp(-logits / max(noise, 1e-12)))
        y = (rng.uniform(size=logits.shape) < p).astype(np.float64)
    return NodeDataset(X=X, y=y, task=task, w_true=w_true)


def pooled_loss(ds: NodeDataset, w: np.ndarray) -> float:
    """Centralized objective at ``w``: mean over ALL samples of the
    per-sample loss (the average of the per-node objectives — every node
    holds the same number of samples)."""
    X = ds.X.reshape(-1, ds.features)
    y = ds.y.reshape(-1)
    z = X @ w
    if ds.task == "linear":
        return float(0.5 * np.mean((z - y) ** 2))
    # logistic negative log-likelihood, numerically stable
    return float(np.mean(np.logaddexp(0.0, z) - y * z))


def centralized_solution(
    ds: NodeDataset, gd_steps: int = 4000, gd_lr: float = 0.5
) -> np.ndarray:
    """Minimizer of the pooled objective — closed form for least squares,
    full-batch gradient descent for logistic regression."""
    X = ds.X.reshape(-1, ds.features)
    y = ds.y.reshape(-1)
    if ds.task == "linear":
        w, *_ = np.linalg.lstsq(X, y, rcond=None)
        return w
    w = np.zeros(ds.features)
    m = len(y)
    # lr scaled by the logistic Hessian bound ||X||^2 / (4m)
    L = 0.25 * np.linalg.norm(X, 2) ** 2 / m
    lr = gd_lr / max(L, 1e-12)
    for _ in range(gd_steps):
        g = X.T @ (1.0 / (1.0 + np.exp(-(X @ w))) - y) / m
        w = w - lr * g
        if np.linalg.norm(g) < 1e-12:
            break
    return w
