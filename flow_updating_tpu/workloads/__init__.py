"""Workloads built ON TOP of the aggregation substrate.

The protocol layer (``models/``) computes mass-conserving averages of
whatever payload the nodes carry; with vector payloads
(``models/state.py``: ``(N, D)`` values) that payload can be a *model
parameter vector*, which turns the simulator into a decentralized-learning
engine: local compute mutates each node's payload, Flow-Updating rounds
are the communication-efficient model-averaging step.

First workload: **gossip-SGD / decentralized FedAvg**
(:mod:`flow_updating_tpu.workloads.gossip_sgd`) — each node holds a
parameter vector and a private synthetic dataset
(:mod:`flow_updating_tpu.workloads.data`), runs local gradient steps, and
averages over the gossip graph, optionally with periodic exact global
averaging (the Gossip-PGA schedule of arXiv:2105.09080; graph-structured
communication efficiency per arXiv:2506.10607).

Entry points: the ``flow-updating-tpu train`` CLI subcommand,
``examples/gossip_sgd.py``, and the classes re-exported here.
"""

from flow_updating_tpu.workloads.data import (  # noqa: F401
    NodeDataset,
    centralized_solution,
    make_dataset,
)
from flow_updating_tpu.workloads.gossip_sgd import (  # noqa: F401
    GossipSGDConfig,
    GossipSGDTrainer,
    per_feature_mass_residual,
    train_grid,
)
