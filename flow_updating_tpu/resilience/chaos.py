"""Chaos harness: infrastructure faults as data, recovery proven per
fault.

The scenario registry (PR 9) closed the conformance loop over
*protocol* adversaries; this module closes it over the
*infrastructure* layer.  Each registered fault is injected into a real
run **in a subprocess** (SIGKILL is a real SIGKILL — no atexit, no
flush), the declared recovery machinery is exercised, and the result is
a ``flow-updating-recovery-report/v1`` manifest that must pass
``doctor --strict`` — while the same fault with recovery *disabled*
(``perturb=True``) must FAIL it, and ``inspect --blame`` must name the
planted fault at rank 1 from the recovery evidence alone.

Registry (:data:`CHAOS_REGISTRY`):

========================  ==============================================
fault                     what is planted / what must hold
========================  ==============================================
``kill_at_segment``       SIGKILL between two scripted ops; recover()
                          replays the WAL — state digest bit-exact vs
                          the uninterrupted control
``kill_mid_checkpoint``   SIGKILL between a ring archive's temp write
                          and its atomic rename; the stale temp is
                          swept, the previous archive recovers, digest
                          bit-exact
``truncate_wal_tail``     the journal's last frame torn after the
                          kill; the tail truncates cleanly and the
                          resumed script re-applies the lost op —
                          digest bit-exact
``corrupt_newest_ckpt``   the newest ring archive torn (size shrinks);
                          recovery falls back to the next, replays a
                          longer WAL suffix — digest bit-exact
``bitflip_archive``       one byte flipped in the newest archive (size
                          intact); the integrity sidecar classifies it,
                          recovery falls back — digest bit-exact
``nan_poison_lane``       one active query lane's ledgers poisoned with
                          NaN; the watchdog quarantines it
                          mass-neutrally — every OTHER lane bit-exact
                          vs an unpoisoned control, free-lane residual
                          exactly 0.0
``admission_storm``       3x lane capacity submitted in one burst; the
                          admission backoff bounds degraded mode and
                          the queue drains
========================  ==============================================

The scripted run is deterministic from ``(kind, seed, sizes)`` alone
and journals exactly one WAL record per op, so a recovered engine
resumes the script at ``ops[wal.last_seq:]`` — how the harness (and any
real driver) continues where the dead process stopped.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys

import numpy as np

#: State leaves carrying a trailing query-lane axis (the per-lane
#: bit-exactness comparison slices these around the poisoned lane).
_LANE_LEAVES = ("value", "flow", "est", "last_avg", "pending_flow",
                "pending_est", "buf_flow", "buf_est")


@dataclasses.dataclass(frozen=True)
class ChaosFault:
    """One registered infra fault (module docstring)."""

    name: str
    summary: str
    kind: str                  # "service" | "query"
    kill: str | None = None    # "op" | "mid_checkpoint"
    tamper: str | None = None  # "truncate_wal"|"truncate_ckpt"|"bitflip"
    inject: str | None = None  # "nan_lane" | "storm"
    watchdog: bool = False
    drain_tail: int = 0        # extra run ops appended to the script


CHAOS_REGISTRY = {f.name: f for f in (
    ChaosFault(
        "kill_at_segment",
        "SIGKILL at a scripted op boundary; WAL replay restores the "
        "exact timeline",
        kind="query", kill="op"),
    ChaosFault(
        "kill_mid_checkpoint",
        "SIGKILL between a ring archive's temp write and its rename; "
        "the stale temp is swept and the previous archive recovers",
        kind="service", kill="mid_checkpoint"),
    ChaosFault(
        "truncate_wal_tail",
        "journal torn mid-frame after the kill; the tail truncates "
        "cleanly and the lost op is re-applied by the resumed script",
        kind="service", kill="op", tamper="truncate_wal"),
    ChaosFault(
        "corrupt_newest_ckpt",
        "newest ring archive torn (size shrinks); recovery falls back "
        "to the next archive",
        kind="query", kill="op", tamper="truncate_ckpt"),
    ChaosFault(
        "bitflip_archive",
        "one byte flipped inside the newest archive (size intact); "
        "the integrity sidecar classifies it and recovery falls back",
        kind="service", kill="op", tamper="bitflip"),
    ChaosFault(
        "nan_poison_lane",
        "one active lane's edge ledgers poisoned with NaN; the "
        "watchdog quarantines it mass-neutrally",
        kind="query", inject="nan_lane", watchdog=True, drain_tail=6),
    ChaosFault(
        "admission_storm",
        "3x lane capacity submitted in one burst; admission backoff "
        "bounds degraded mode until the queue drains",
        kind="query", inject="storm", watchdog=True, drain_tail=24),
)}


def get_fault(name: str) -> ChaosFault:
    try:
        return CHAOS_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown chaos fault {name!r}; registered: "
            f"{', '.join(sorted(CHAOS_REGISTRY))}") from None


# ---- the deterministic scripted run --------------------------------------

def service_capacity(nodes: int) -> int:
    """Spare node slots the scripted service run budgets for joins —
    shared by the engine constructor and the script's free-list mirror
    (they must agree for journaled joins to replay into the same
    slots)."""
    return nodes + max(4, nodes // 8)


def scripted_ops(kind: str, n_ops: int, seed: int, nodes: int,
                 lanes: int, drain_tail: int = 0) -> list:
    """The scripted event stream, computed from the arguments alone
    (no engine state) so the child, the recovering parent and the
    control all agree: one journaled WAL record per op."""
    rng = np.random.default_rng(seed)
    ops: list = []
    if kind == "service":
        free = list(range(nodes, service_capacity(nodes)))
        held: list = []
        while len(ops) < n_ops:
            r = rng.random()
            if r < 0.2 and held:
                slot = held.pop(0)
                free.append(slot)
                free.sort()
                ops.append({"op": "leave", "ids": [slot]})
            elif r < 0.45 and free:
                slot = free.pop(0)
                anchor = int(rng.integers(0, nodes))
                held.append(slot)
                ops.append({"op": "join", "value": float(rng.random())})
                ops.append({"op": "add_edges",
                            "pairs": [[slot, anchor]]})
            elif r < 0.6:
                i = int(rng.integers(0, nodes))
                ops.append({"op": "update", "ids": [i],
                            "values": [float(rng.random())]})
            else:
                ops.append({"op": "run",
                            "segments": int(rng.integers(1, 4))})
    else:
        while len(ops) < n_ops:
            r = rng.random()
            if r < 0.4:
                m = int(rng.integers(1, max(2, min(lanes, nodes // 4))))
                cohort = np.sort(rng.choice(
                    nodes, size=m, replace=False)).tolist()
                ops.append({"op": "submit",
                            "values": rng.random(m).tolist(),
                            "cohort": [int(i) for i in cohort]})
            elif r < 0.5:
                i = int(rng.integers(0, nodes))
                ops.append({"op": "suspend", "ids": [i]})
                ops.append({"op": "resume", "ids": [i]})
            else:
                ops.append({"op": "run",
                            "segments": int(rng.integers(1, 4))})
    ops = ops[:n_ops]
    ops.extend({"op": "run", "segments": 4} for _ in range(drain_tail))
    return ops


def build_engine(kind: str, nodes: int, lanes: int,
                 segment_rounds: int, seed: int, drop_rate: float,
                 eps: float = 1e-3):
    """The scripted run's engine — an ER topology (fast mixing keeps
    the scripts short), drop>0 by default (the acceptance criteria
    include loss + churn + active lanes)."""
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.topology.generators import erdos_renyi

    topo = erdos_renyi(nodes, avg_degree=8.0, seed=seed)
    cfg = RoundConfig.fast(variant="collectall", drop_rate=drop_rate)
    if kind == "service":
        from flow_updating_tpu.service import ServiceEngine

        return ServiceEngine(
            topo, service_capacity(nodes),
            degree_budget=int(topo.out_deg.max()) + 8,
            config=cfg, segment_rounds=segment_rounds, seed=seed)
    from flow_updating_tpu.query import QueryFabric

    return QueryFabric(
        topo, lanes=lanes, capacity=nodes, config=cfg,
        segment_rounds=segment_rounds, seed=seed, conv_eps=eps,
        # storms intentionally overflow the queue: the admission SLO
        # under test is the backoff bound, not the latency budget
        admission_slo_rounds=10_000 * segment_rounds)


def apply_op(engine, kind: str, op: dict,
             segment_rounds: int) -> None:
    o = op["op"]
    if o == "run":
        engine.run(op["segments"] * segment_rounds)
    elif o == "join":
        if kind == "service":
            engine.join(op["value"])
        else:
            engine.join()
    elif o == "leave":
        engine.leave(op["ids"])
    elif o == "update":
        engine.update(op["ids"], np.asarray(op["values"]))
    elif o == "add_edges":
        engine.add_edges([tuple(p) for p in op["pairs"]])
    elif o == "suspend":
        engine.suspend(op["ids"])
    elif o == "resume":
        engine.resume(op["ids"])
    elif o == "submit":
        engine.submit(np.asarray(op["values"]), cohort=op["cohort"])
    else:
        raise ValueError(f"unknown scripted op {o!r}")


def pick_kill_op(ops: list, seed: int) -> int:
    """A seeded kill point in the middle half of the script, placed
    right after a state-CHANGING event op — so at least one journaled
    record is guaranteed to sit between the last possible ring
    checkpoint (checkpoints only happen inside run ops) and the kill,
    which is exactly what the recovery-disabled control must lose."""
    rng = np.random.default_rng(seed + 7)
    lo, hi = len(ops) // 4, 3 * len(ops) // 4
    candidates = [i for i in range(lo, hi)
                  if ops[i - 1]["op"] in ("update", "submit", "join",
                                          "add_edges", "leave")]
    if not candidates:
        candidates = [max(lo, 1)]
    return int(candidates[int(rng.integers(0, len(candidates)))])


def pick_poison_op(ops: list) -> int:
    """The first run op after a submit — an active lane is guaranteed
    at the next boundary."""
    seen_submit = False
    for i, op in enumerate(ops):
        if op["op"] == "submit":
            seen_submit = True
        elif seen_submit and op["op"] == "run":
            return i
    raise ValueError("script has no submit-then-run prefix to poison")


# ---- the child (the real run a fault is injected into) -------------------

def _child_main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="chaos-child")
    ap.add_argument("--kind", required=True,
                    choices=("service", "query"))
    ap.add_argument("--dir", required=True)
    ap.add_argument("--result", required=True,
                    help="where the surviving child writes its blocks")
    ap.add_argument("--final", default=None,
                    help="final checkpoint path (surviving children)")
    ap.add_argument("--nodes", type=int, required=True)
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--segment-rounds", type=int, default=8)
    ap.add_argument("--ops", type=int, required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--drop-rate", type=float, default=0.05)
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--retain", type=int, default=3)
    ap.add_argument("--drain-tail", type=int, default=0)
    ap.add_argument("--kill-op", type=int, default=-1)
    ap.add_argument("--kill-mid-ckpt", type=int, default=-1,
                    help="SIGKILL during the Nth ring archive write")
    ap.add_argument("--poison-op", type=int, default=-1)
    ap.add_argument("--storm-op", type=int, default=-1)
    ap.add_argument("--watchdog", action="store_true")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    engine = build_engine(args.kind, args.nodes, args.lanes,
                          args.segment_rounds, args.seed,
                          args.drop_rate)
    if args.watchdog:
        engine.attach_watchdog()
    if args.kill_mid_ckpt >= 0:
        from flow_updating_tpu.utils import checkpoint as ck

        writes = {"n": 0}

        def _crash(path: str) -> None:
            if os.path.basename(path).startswith("ckpt-"):
                writes["n"] += 1
                if writes["n"] == args.kill_mid_ckpt:
                    os.kill(os.getpid(), signal.SIGKILL)

        ck._CRASH_BEFORE_REPLACE = _crash
    engine.enable_durability(args.dir,
                             checkpoint_every=args.checkpoint_every,
                             retain=args.retain)
    ops = scripted_ops(args.kind, args.ops, args.seed, args.nodes,
                       args.lanes, drain_tail=args.drain_tail)
    planted = {}
    for i, op in enumerate(ops):
        if i == args.kill_op:
            os.kill(os.getpid(), signal.SIGKILL)
        if i == args.poison_op:
            import jax.numpy as jnp

            lane = next(ln for ln, q in enumerate(engine._lane_q)
                        if q is not None)
            st = engine.svc.state
            engine.svc.state = st.replace(
                est=st.est.at[:, lane].set(jnp.nan),
                flow=st.flow.at[:, lane].set(jnp.nan))
            planted["poisoned_lane"] = int(lane)
            planted["poison_op"] = i
        if i == args.storm_op:
            rng = np.random.default_rng(args.seed + 13)
            for _ in range(3 * args.lanes):
                member = int(rng.integers(0, args.nodes))
                engine.submit([float(rng.random())], cohort=[member])
            planted["storm_op"] = i
            planted["storm_queries"] = 3 * args.lanes
        apply_op(engine, args.kind, op, args.segment_rounds)
    if args.final:
        engine.save_checkpoint(args.final)
    result = {
        "planted": planted,
        "digest": engine.state_digest(),
        "clock": int(engine.clock),
        "recovery": engine.resilience_block(),
        "serving_trace": engine.serving_trace_block(),
    }
    if args.kind == "query":
        result["query"] = engine.query_block()
    else:
        result["service"] = engine.service_block()
    with open(args.result, "w") as f:
        json.dump(result, f, indent=1, default=str)
    return 0


# ---- tamper (what the fault does to the dead process's directory) --------

def _newest_ckpt(directory: str) -> str:
    from flow_updating_tpu.resilience.ring import CheckpointRing

    cands = CheckpointRing(directory).candidates()
    if not cands:
        raise ValueError(f"{directory}: ring is empty, nothing to "
                         "tamper with")
    return cands[0]["path"]


def apply_tamper(directory: str, tamper: str) -> dict:
    """Damage the durability directory the way the fault declares.
    Returns the ground-truth detail block."""
    from flow_updating_tpu.resilience.recover import WAL_NAME

    if tamper == "truncate_wal":
        path = os.path.join(directory, WAL_NAME)
        size = os.path.getsize(path)
        cut = min(7, size - 9)           # tear the last frame mid-way
        with open(path, "r+b") as f:
            f.truncate(size - cut)
        return {"tampered": os.path.basename(path),
                "bytes_cut": int(cut)}
    if tamper == "truncate_ckpt":
        path = _newest_ckpt(directory)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size * 3 // 5, 1))
        return {"tampered": os.path.basename(path),
                "bytes_cut": int(size - size * 3 // 5)}
    if tamper == "bitflip":
        path = _newest_ckpt(directory)
        size = os.path.getsize(path)
        off = size // 2
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
        return {"tampered": os.path.basename(path),
                "bitflip_offset": int(off)}
    raise ValueError(f"unknown tamper {tamper!r}")


# ---- the parent-side runner ---------------------------------------------

def _spawn_child(fault: ChaosFault, *, directory: str, result: str,
                 final: str | None, nodes: int, lanes: int,
                 segment_rounds: int, n_ops: int, seed: int,
                 drop_rate: float, checkpoint_every: int, retain: int,
                 kill_op: int, poison_op: int, storm_op: int,
                 watchdog: bool) -> subprocess.CompletedProcess:
    cmd = [sys.executable, "-m",
           "flow_updating_tpu.resilience.chaos",
           "--kind", fault.kind, "--dir", directory,
           "--result", result,
           "--nodes", str(nodes), "--lanes", str(lanes),
           "--segment-rounds", str(segment_rounds),
           "--ops", str(n_ops), "--seed", str(seed),
           "--drop-rate", str(drop_rate),
           "--checkpoint-every", str(checkpoint_every),
           "--retain", str(retain),
           "--drain-tail", str(fault.drain_tail)]
    if final:
        cmd += ["--final", final]
    if kill_op >= 0:
        cmd += ["--kill-op", str(kill_op)]
    if fault.kill == "mid_checkpoint":
        cmd += ["--kill-mid-ckpt", "3"]
    if poison_op >= 0:
        cmd += ["--poison-op", str(poison_op)]
    if storm_op >= 0:
        cmd += ["--storm-op", str(storm_op)]
    if watchdog:
        cmd += ["--watchdog"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(cmd, env=env, capture_output=True, text=True)


def _run_control(fault: ChaosFault, ops: list, *, nodes: int,
                 lanes: int, segment_rounds: int, seed: int,
                 drop_rate: float):
    """The uninterrupted in-process control run (no durability; same
    watchdog arming so the boundary schedule matches)."""
    engine = build_engine(fault.kind, nodes, lanes, segment_rounds,
                          seed, drop_rate)
    if fault.watchdog:
        engine.attach_watchdog()
    for op in ops:
        apply_op(engine, fault.kind, op, segment_rounds)
    return engine


def _compare_lanes(recovered_svc_state, control_svc_state,
                   poisoned: int) -> dict:
    """Bit-exactness of every lane EXCEPT the poisoned one, plus the
    whole payload-independent control plane."""
    bad = []
    for name in recovered_svc_state.__dataclass_fields__:
        a = np.asarray(getattr(recovered_svc_state, name))
        b = np.asarray(getattr(control_svc_state, name))
        if name in _LANE_LEAVES:
            keep = [ln for ln in range(a.shape[-1]) if ln != poisoned]
            a, b = a[..., keep], b[..., keep]
        if not np.array_equal(a, b):
            bad.append(name)
    return {"exact": not bad, "kind": "lanes_except_poisoned",
            "poisoned_lane": int(poisoned), "diverged_leaves": bad}


def run_chaos(name: str, *, nodes: int = 128, lanes: int = 8,
              segment_rounds: int = 8, n_ops: int = 28, seed: int = 0,
              drop_rate: float = 0.05, checkpoint_every: int = 2,
              retain: int = 3, outdir: str = "obs-artifacts",
              perturb: bool = False) -> dict:
    """Run one registered fault end to end (module docstring).

    Returns ``{"fault", "manifest_path", "checks", "overall",
    "blame_top", ...}``; the manifest passes ``doctor --strict`` for a
    healthy recovery and FAILS under ``perturb=True`` (recovery
    disabled) — both directions are the chaos conformance contract."""
    import tempfile

    from flow_updating_tpu.obs import health
    from flow_updating_tpu.obs.inspect import blame_recovery
    from flow_updating_tpu.obs.report import (
        build_recovery_manifest,
        write_report,
    )
    from flow_updating_tpu.resilience.recover import recover

    fault = get_fault(name)
    os.makedirs(outdir, exist_ok=True)
    scratch = tempfile.mkdtemp(prefix=f"chaos-{name}-")
    directory = os.path.join(scratch, "durability")
    result_path = os.path.join(scratch, "child_result.json")
    final_path = os.path.join(scratch, "final.npz")

    ops = scripted_ops(fault.kind, n_ops, seed, nodes, lanes,
                       drain_tail=fault.drain_tail)
    kill_op = pick_kill_op(ops, seed) if fault.kill == "op" else -1
    poison_op = pick_poison_op(ops) if fault.inject == "nan_lane" \
        else -1
    storm_op = pick_poison_op(ops) if fault.inject == "storm" else -1
    use_watchdog = fault.watchdog and not perturb

    proc = _spawn_child(
        fault, directory=directory, result=result_path,
        final=final_path if fault.inject else None,
        nodes=nodes, lanes=lanes, segment_rounds=segment_rounds,
        n_ops=n_ops, seed=seed, drop_rate=drop_rate,
        checkpoint_every=checkpoint_every, retain=retain,
        kill_op=kill_op, poison_op=poison_op, storm_op=storm_op,
        watchdog=use_watchdog)
    killed = proc.returncode == -signal.SIGKILL
    if fault.kill and not killed:
        raise RuntimeError(
            f"chaos {name}: child was supposed to die by SIGKILL, got "
            f"rc={proc.returncode}\n{proc.stderr[-2000:]}")
    if not fault.kill and proc.returncode != 0:
        raise RuntimeError(
            f"chaos {name}: child failed rc={proc.returncode}\n"
            f"{proc.stderr[-2000:]}")

    ground_truth = {"fault": name, "summary": fault.summary,
                    "kind": fault.kind, "perturb": bool(perturb),
                    "seed": seed, "ops": len(ops)}
    if kill_op >= 0:
        ground_truth["kill_op"] = kill_op
    if fault.tamper:
        ground_truth.update(apply_tamper(directory, fault.tamper))

    recovery_block: dict
    service_block = query_block = None
    serving_trace = None
    verify = None
    timings: dict = {}

    if fault.kill:
        if perturb and fault.tamper in ("truncate_ckpt", "bitflip"):
            # recovery-disabled control: no ring fallback — try ONLY
            # the newest archive and report the dead end
            from flow_updating_tpu.resilience.ring import CheckpointRing

            ringo = CheckpointRing(directory, every=checkpoint_every,
                                   retain=retain)
            cand = ringo.candidates()[0]
            try:
                build_cls = None
                if fault.kind == "query":
                    from flow_updating_tpu.query import QueryFabric \
                        as build_cls
                else:
                    from flow_updating_tpu.service import ServiceEngine \
                        as build_cls
                build_cls.restore_checkpoint(cand["path"])
                status = "used"
            except ValueError as exc:
                status = "restore-failed"
                cand = {**cand, "error": str(exc)}
            recovery_block = {
                "dir": directory, "kind": fault.kind,
                "ring": {**ringo.block(),
                         "scanned": [{**cand, "status": status}],
                         "used": None, "fallbacks": 1},
                "ground_truth": ground_truth,
            }
        else:
            import time as _time

            t0 = _time.perf_counter()
            engine = recover(directory, kind=fault.kind,
                             replay=not perturb)
            timings["recover_s"] = round(_time.perf_counter() - t0, 4)
            resume_from = engine._wal.last_seq
            resume_error = None
            for op in ops[resume_from:]:
                try:
                    apply_op(engine, fault.kind, op, segment_rounds)
                except (ValueError, RuntimeError) as exc:
                    if not perturb:
                        raise
                    # the recovery-disabled control is ALLOWED to break
                    # — a lost join makes later ops reference a
                    # non-member; the manifest records the wreckage
                    resume_error = f"{type(exc).__name__}: {exc}"
                    break
            control = _run_control(
                fault, ops, nodes=nodes, lanes=lanes,
                segment_rounds=segment_rounds, seed=seed,
                drop_rate=drop_rate)
            verify = {
                "exact": resume_error is None
                and engine.state_digest() == control.state_digest(),
                "kind": "state_digest",
                "recovered_digest": engine.state_digest(),
                "control_digest": control.state_digest(),
                "resumed_ops": len(ops) - resume_from,
            }
            if resume_error is not None:
                verify["resume_error"] = resume_error
            recovery_block = engine.resilience_block() or {}
            recovery_block["verify"] = verify
            recovery_block["ground_truth"] = ground_truth
            if fault.kind == "query":
                query_block = engine.query_block()
            else:
                service_block = engine.service_block()
            # the flight recorder survived the SIGKILL with the engine:
            # its spans/metrics rode the ring checkpoint and the replay
            # re-fired the rest — doctor's span_complete judges the
            # continuity (and FAILS the replay-disabled perturbation)
            serving_trace = engine.serving_trace_block()
    else:
        # inject faults: the child survived and wrote its own blocks
        with open(result_path) as f:
            child = json.load(f)
        ground_truth.update(child.get("planted") or {})
        recovery_block = child.get("recovery") or {
            "dir": directory, "kind": fault.kind}
        recovery_block["ground_truth"] = ground_truth
        query_block = child.get("query")
        service_block = child.get("service")
        serving_trace = child.get("serving_trace")
        if fault.inject == "nan_lane" and not perturb:
            from flow_updating_tpu.query import QueryFabric

            recovered = QueryFabric.restore_checkpoint(final_path)
            control = _run_control(
                fault, ops, nodes=nodes, lanes=lanes,
                segment_rounds=segment_rounds, seed=seed,
                drop_rate=drop_rate)
            verify = _compare_lanes(
                recovered.svc.state, control.svc.state,
                ground_truth["poisoned_lane"])
            recovery_block["verify"] = verify

    suffix = "_perturbed" if perturb else ""
    manifest_path = os.path.join(outdir, f"chaos_{name}{suffix}.json")
    manifest = build_recovery_manifest(
        argv=["chaos", name] + (["--perturb"] if perturb else []),
        recovery=recovery_block, service=service_block,
        query=query_block, timings=timings or None,
        extra=({"serving_trace": serving_trace}
               if serving_trace else None))
    write_report(manifest_path, manifest)

    checks = health.check_recovery(recovery_block)
    if serving_trace:
        # the flight recorder rides the same gate: a recovery whose
        # span chains have gaps (or whose counters disagree with the
        # census) fails the conformance loop, not just the doctor CLI
        checks = checks + health.check_serving_trace(
            serving_trace, query=query_block, recovery=recovery_block)
    blame = blame_recovery(manifest)
    return {
        "fault": name,
        "perturb": bool(perturb),
        "manifest_path": manifest_path,
        "overall": health.overall(checks),
        "exit_code": health.exit_code(checks, strict=True),
        "checks": [c.to_jsonable() for c in checks],
        "blame_top": blame["top"],
        "blame": blame["ranked"][:3],
        "verify": verify,
        "timings": timings,
    }


if __name__ == "__main__":
    sys.exit(_child_main())
