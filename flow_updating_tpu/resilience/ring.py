"""Automatic checkpoint ring: every K segments, N retained, corrupt
newest falls back to the next.

Rides :mod:`flow_updating_tpu.utils.checkpoint`'s atomic write path
(temp file + ``os.replace``: a crash mid-write leaves a stale ``.tmp.*``
and NO final archive — never a truncated file at the final path).  On
top of it the ring adds:

* **cadence** — the owning engine calls :meth:`CheckpointRing.tick`
  after each compiled segment batch; every ``every`` segments one
  archive ``ckpt-<index>.npz`` is written carrying the WAL sequence it
  is consistent with (``meta["resilience"]["wal_seq"]``);
* **retention** — the oldest archives beyond ``retain`` are pruned
  after each successful write (never before: the new archive must be
  durable first);
* **integrity sidecars** — each archive gets a ``.sha.json`` sidecar
  (size + sha256, written atomically AFTER the archive) so a recovery
  scan can *classify* damage: ``truncated`` (size shrank — a torn
  copy), ``bitflipped`` (size intact, digest off), ``unindexed`` (the
  crash hit between archive and sidecar — the archive itself is still
  trustworthy and stays a candidate);
* **fallback** — :meth:`candidates` yields archives newest-first;
  recovery (resilience/recover.py) tries each until one restores,
  recording every skip as evidence for the doctor's ``ring_integrity``
  check and ``inspect --blame``.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time

_CKPT_RE = re.compile(r"^ckpt-(\d{8})\.npz$")


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class CheckpointRing:
    def __init__(self, directory: str, *, every: int = 8,
                 retain: int = 3):
        if every < 1:
            raise ValueError(f"checkpoint every={every} must be >= 1")
        if retain < 1:
            raise ValueError(f"retain={retain} must be >= 1")
        self.dir = directory
        self.every = int(every)
        self.retain = int(retain)
        self._segments_since = 0
        self.written_total = 0
        #: Wall-time accounting for the serving metrics plane
        #: (obs/metrics.py): total/last archive write seconds.
        self.write_seconds_total = 0.0
        self.last_write_s = 0.0
        os.makedirs(directory, exist_ok=True)

    # ---- paths -----------------------------------------------------------
    def _path(self, index: int) -> str:
        return os.path.join(self.dir, f"ckpt-{index:08d}.npz")

    @staticmethod
    def _sidecar(path: str) -> str:
        return path + ".sha.json"

    def indices(self) -> list:
        """Existing archive indices, ascending."""
        out = []
        for name in os.listdir(self.dir):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # ---- write path ------------------------------------------------------
    def tick(self, owner, wal_seq: int, segments: int = 1) -> str | None:
        """Count ``segments`` completed segments; write a ring archive
        when the cadence fires.  Returns the new archive path or None."""
        self._segments_since += int(segments)
        if self._segments_since < self.every:
            return None
        return self.write(owner, wal_seq)

    def write(self, owner, wal_seq: int) -> str:
        """Write one ring archive now (atomic), sidecar it, prune the
        tail beyond ``retain``.  ``owner`` is a ServiceEngine or
        QueryFabric (anything with ``save_checkpoint(path,
        extra_meta=)`` and ``clock``)."""
        idx = (self.indices() or [-1])[-1] + 1
        path = self._path(idx)
        t0 = time.perf_counter()
        owner.save_checkpoint(path, extra_meta={"resilience": {
            "wal_seq": int(wal_seq),
            "ring_index": idx,
            "clock": int(owner.clock),
        }})
        side = {"size": os.path.getsize(path),
                "sha256": _sha256_file(path)}
        tmp = f"{self._sidecar(path)}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(side, f)
        os.replace(tmp, self._sidecar(path))
        self.last_write_s = time.perf_counter() - t0
        self.write_seconds_total += self.last_write_s
        self._segments_since = 0
        self.written_total += 1
        for old in self.indices()[:-self.retain]:
            for p in (self._path(old), self._sidecar(self._path(old))):
                if os.path.exists(p):
                    os.remove(p)
        return path

    # ---- recovery scan ---------------------------------------------------
    def classify(self, path: str) -> str:
        """Integrity verdict for one archive from its sidecar (module
        docstring): valid / truncated / bitflipped / unindexed /
        missing."""
        if not os.path.exists(path):
            return "missing"
        side_path = self._sidecar(path)
        if not os.path.exists(side_path):
            return "unindexed"
        try:
            with open(side_path) as f:
                side = json.load(f)
        except (OSError, ValueError):
            return "unindexed"
        size = os.path.getsize(path)
        if size != side.get("size"):
            return "truncated"
        if _sha256_file(path) != side.get("sha256"):
            return "bitflipped"
        return "valid"

    def candidates(self) -> list:
        """Archives newest-first, each ``{"path", "index", "integrity"}``
        — the fallback order recovery walks.  Classified-damaged entries
        are still listed (the restore attempt is the ground truth; the
        classification is the evidence)."""
        out = []
        for idx in reversed(self.indices()):
            path = self._path(idx)
            out.append({"path": path, "index": idx,
                        "integrity": self.classify(path)})
        return out

    def block(self) -> dict:
        """The manifest's ``ring`` sub-block (obs/report.py)."""
        return {
            "every_segments": self.every,
            "retain": self.retain,
            "written_total": self.written_total,
            "kept": len(self.indices()),
            "write_seconds_total": self.write_seconds_total,
        }
