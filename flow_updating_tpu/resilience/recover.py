"""Arm durability on a live engine; rebuild one from its directory.

A durability directory is the whole crash-safety contract in one place:

* ``resilience.json`` — which engine kind lives here (service/query),
  the ring cadence/retention, the fsync policy, the watchdog config
  (written once at :func:`arm_durability`; rewritten when a watchdog
  attaches later);
* ``wal.log`` — the CRC-framed event journal
  (:mod:`flow_updating_tpu.resilience.wal`);
* ``ckpt-*.npz`` (+ ``.sha.json`` sidecars) — the checkpoint ring
  (:mod:`flow_updating_tpu.resilience.ring`).

:func:`recover` is the SIGKILL-at-any-point path: walk the ring newest
-first until an archive restores (recording every skip as evidence),
truncate the WAL's torn tail, replay every journaled event after the
checkpoint's ``wal_seq`` through the engine's own event methods — the
events are O(event) deterministic mask edits, so the recovered state is
bit-exact vs the uninterrupted run (the chaos harness and
tests/test_resilience.py assert the digest) — then re-arm durability so
the recovered engine keeps journaling where the dead process stopped.
"""

from __future__ import annotations

import glob
import json
import os

import numpy as np

from flow_updating_tpu.resilience.ring import CheckpointRing
from flow_updating_tpu.resilience.wal import WriteAheadLog

CONFIG_NAME = "resilience.json"
WAL_NAME = "wal.log"


def _write_config(directory: str, doc: dict) -> None:
    tmp = os.path.join(directory, f"{CONFIG_NAME}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, os.path.join(directory, CONFIG_NAME))


def read_config(directory: str) -> dict:
    path = os.path.join(directory, CONFIG_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        raise ValueError(
            f"{directory}: no {CONFIG_NAME} — not a durability "
            "directory (arm one with ServiceEngine.enable_durability / "
            "QueryFabric.enable_durability, or the serve/query CLIs' "
            "--wal DIR)") from None
    except ValueError as exc:
        raise ValueError(
            f"{path}: corrupt durability config ({exc}) — re-arm the "
            "directory (the WAL and ring archives are untouched)") from exc


def arm_durability(engine, directory: str, *, kind: str,
                   checkpoint_every: int = 8, retain: int = 3,
                   fsync: bool = True) -> None:
    """Attach a WAL + checkpoint ring to a live engine.  Writes the
    directory config, opens a FRESH journal (a used directory is
    refused — continuing it would splice two engines' timelines; only
    :func:`recover` continues a journal), and writes the genesis
    checkpoint so a crash one event later already has a recovery
    base."""
    if engine._wal is not None:
        raise ValueError(
            "durability is already armed on this engine (one WAL per "
            "engine; re-arming would fork the journal)")
    os.makedirs(directory, exist_ok=True)
    # a directory that already holds a journal or ring belongs to a
    # PREVIOUS engine: continuing it with a fresh engine would splice
    # two timelines — recovery would replay this engine's records onto
    # the old engine's checkpoint
    ring_probe = CheckpointRing(directory, every=checkpoint_every,
                                retain=retain)
    wal_path = os.path.join(directory, WAL_NAME)
    if ring_probe.indices() or os.path.exists(wal_path):
        raise ValueError(
            f"{directory}: already a durability directory (journal/"
            "ring present from a previous engine) — recover() it, or "
            "arm a fresh directory; mixing engines in one journal "
            "would make recovery replay a spliced timeline")
    wd = getattr(engine, "_watchdog", None)
    _write_config(directory, {
        "kind": kind,
        "checkpoint_every": int(checkpoint_every),
        "retain": int(retain),
        "fsync": bool(fsync),
        "watchdog": wd.config.to_jsonable() if wd is not None else None,
    })
    ring = ring_probe
    wal = WriteAheadLog(wal_path, fsync=fsync)
    engine._wal = wal
    engine._ring = ring
    engine._resil_dir = directory
    engine._wal_applied_seq = wal.last_seq
    if not ring.indices():
        ring.write(engine, wal.last_seq)


def _restore_meta(path: str) -> dict:
    """The archive's ``meta['resilience']`` block (wal_seq binding)."""
    from flow_updating_tpu.utils.checkpoint import (
        _open_archive,
        _read_manifest,
    )

    with _open_archive(path) as z:
        manifest = _read_manifest(z, path)
    return (manifest.get("service") or {}).get("resilience") or {}


def _sweep_stale_tmp(directory: str) -> list:
    """Temp files an interrupted atomic write left behind (SIGKILL
    between temp write and ``os.replace``).  They are garbage by
    construction — the final path was never touched — but their
    presence is recovery evidence (``inspect --blame`` reads a
    mid-checkpoint-write kill off it), so they are swept and counted."""
    stale = sorted(glob.glob(os.path.join(directory, "*.tmp.*")))
    for path in stale:
        os.remove(path)
    return [os.path.basename(p) for p in stale]


def recover(directory: str, *, kind: str | None = None,
            replay: bool = True):
    """Rebuild the engine journaled in ``directory`` (module
    docstring).  ``kind`` overrides the directory config (it must
    match what was armed); ``replay=False`` restores the newest valid
    checkpoint WITHOUT replaying the WAL — the chaos harness's
    recovery-disabled negative control, never the production path."""
    cfg = read_config(directory)
    kind = kind or cfg.get("kind", "service")
    if kind != cfg.get("kind"):
        raise ValueError(
            f"{directory}: armed for a {cfg.get('kind')!r} engine, "
            f"recover(kind={kind!r}) cannot reinterpret it")
    stale_tmp = _sweep_stale_tmp(directory)
    ring = CheckpointRing(directory, every=cfg["checkpoint_every"],
                          retain=cfg["retain"])

    if kind == "query":
        from flow_updating_tpu.query import QueryFabric as _cls
    else:
        from flow_updating_tpu.service import ServiceEngine as _cls

    scanned, engine, used = [], None, None
    for cand in ring.candidates():
        if engine is not None:
            scanned.append({**cand, "status": "older-unused"})
            continue
        try:
            engine = _cls.restore_checkpoint(cand["path"])
        except ValueError as exc:
            scanned.append({**cand, "status": "restore-failed",
                            "error": str(exc)})
            continue
        used = {**cand, "status": "used"}
        scanned.append(used)
    if engine is None:
        report = "; ".join(f"{os.path.basename(s['path'])}: "
                           f"{s['integrity']}" for s in scanned)
        raise ValueError(
            f"{directory}: no ring checkpoint restores "
            f"({report or 'ring is empty'}) — the service cannot be "
            "recovered from this directory")
    meta = _restore_meta(used["path"])
    base_seq = int(meta.get("wal_seq", 0))

    # keep_records: the open already CRC-scans the whole journal (and
    # truncates any torn tail); recovery replays from that one pass
    wal = WriteAheadLog(os.path.join(directory, WAL_NAME),
                        fsync=cfg.get("fsync", True),
                        keep_records=True)
    records = wal.records or []
    to_apply = [r for r in records if int(r["seq"]) > base_seq]

    engine._wal = wal
    engine._ring = ring
    engine._resil_dir = directory
    engine._wal_applied_seq = base_seq
    if kind == "query" and cfg.get("watchdog") is not None:
        from flow_updating_tpu.resilience.watchdog import WatchdogConfig

        engine.attach_watchdog(WatchdogConfig.from_jsonable(
            cfg["watchdog"]))

    events = rounds = 0
    if replay:
        engine._replaying = True
        try:
            for rec in to_apply:
                engine._wal_applied_seq = int(rec["seq"])
                _apply_record(engine, kind, rec)
                if rec["kind"] == "run":
                    rounds += int(rec["args"]["rounds"])
                else:
                    events += 1
        finally:
            engine._replaying = False

    engine._recovery = {
        "dir": directory,
        "kind": kind,
        "stale_tmp_swept": stale_tmp,
        "wal": {
            **wal.block(),
            "records_total": len(records),
            "torn_tail": wal.torn_bytes > 0,
        },
        "ring": {
            **ring.block(),
            "scanned": scanned,
            "used": {k: used[k] for k in ("path", "index", "integrity")},
            "fallbacks": sum(1 for s in scanned
                             if s["status"] == "restore-failed"),
        },
        "replay": {
            "enabled": bool(replay),
            "base_wal_seq": base_seq,
            "base_clock": int(meta.get("clock", 0)),
            "records_pending": len(to_apply),
            "records_replayed": len(to_apply) if replay else 0,
            "events_replayed": events,
            "rounds_replayed": rounds,
            "recovered_clock": int(engine.clock),
            "last_seq": wal.last_seq,
        },
    }
    # the flight recorder's continuity marker (obs/spans.py): the span
    # state restored from the checkpoint ends at base_clock, the replay
    # above re-fired the boundary hooks up to recovered_clock — one
    # explicit engine-level span covers the gap and carries the replay
    # evidence, so doctor's ``span_complete`` can PROVE the trace is
    # continuous (and FAIL a replay-disabled control)
    spans = getattr(engine, "spans", None)
    if spans is not None:
        spans.engine_span(
            "recovery", int(meta.get("clock", 0)), int(engine.clock),
            records_pending=len(to_apply),
            records_replayed=len(to_apply) if replay else 0,
            events_replayed=events, rounds_replayed=rounds,
            replay_enabled=bool(replay),
            wal_last_seq=int(wal.last_seq),
            ring_index=int(used["index"]))
    metrics = getattr(engine, "metrics", None)
    if metrics is not None:
        metrics.inc("recoveries_total")
        if replay:
            metrics.inc("wal_records_replayed_total", len(to_apply))
    # post-replay aliasing probe (analysis/aliasing.py): replayed
    # events edit host mirrors in place, so a zero-copy restored leaf
    # would have raced the replay itself — assert the recovered engine
    # holds no mirror-aliased device leaves before handing it back
    from flow_updating_tpu.analysis.aliasing import (
        assert_no_shared_mirrors,
    )

    assert_no_shared_mirrors(engine)
    return engine


def _apply_record(engine, kind: str, rec: dict) -> None:
    """Re-apply one journaled event through the engine's own event
    method (the replay side of the write-ahead contract; journaling is
    suppressed by ``_replaying``)."""
    ev, a = rec["kind"], rec.get("args", {})
    if ev == "run":
        engine.run(int(a["rounds"]))
    elif ev == "join":
        if kind == "query":
            engine.join()
        else:
            engine.join(np.asarray(a["value"], np.float64))
    elif ev == "leave":
        engine.leave(a["ids"])
    elif ev == "update":
        engine.update(a["ids"], np.asarray(a["values"], np.float64))
    elif ev == "add_edges":
        engine.add_edges([tuple(p) for p in a["pairs"]])
    elif ev == "remove_edges":
        engine.remove_edges([tuple(p) for p in a["pairs"]])
    elif ev == "suspend":
        engine.suspend(a["ids"])
    elif ev == "resume":
        engine.resume(a["ids"])
    elif ev == "submit":
        engine.submit(np.asarray(a["values"], np.float64),
                      cohort=a["cohort"], eps=a.get("eps"),
                      tag=a.get("tag"))
    elif ev == "update_query":
        engine.update_query(int(a["qid"]), a["ids"],
                            np.asarray(a["values"], np.float64))
    else:
        raise ValueError(
            f"wal record seq {rec.get('seq')}: unknown event kind "
            f"{ev!r} — the journal was written by a newer version "
            "(or is not a flow_updating_tpu WAL)")
