"""Inline lane watchdog: graceful degradation instead of crash-or-poison.

One NaN'd or divergence-poisoned query lane must not take down the
compiled engine (the payload planes are shared arrays — a NaN column
survives any number of segments, and a crashed service loses every
lane).  The watchdog rides the query fabric's existing device-side lane
probe — the same five ``(lanes,)`` vectors every segment boundary
already computes, so detection adds ZERO compiles and no extra device
reads — and turns per-lane pathology into *lane quarantine*:

* **detection** (:meth:`Watchdog.inspect`): per active lane, a
  non-finite probe entry (``nan``), an estimate scale blown
  ``diverge_factor``x past the query's own value scale
  (``divergence``), or a spread that stopped shrinking for
  ``stall_boundaries`` boundaries while still above the query's eps
  (``stall``; 0 disables);
* **quarantine** — the lane's payload planes are scrubbed back to the
  all-zero fixed point (exactly the retirement scrub — mass-neutral,
  free-lane residual exactly 0.0, asserted per action) and the lane
  returns to the free heap; the query is marked ``quarantined``.  All
  other lanes are untouched: the control plane is payload-independent,
  so their trajectories stay bit-exact vs an unpoisoned run
  (tests/test_resilience.py pins this);
* **admission backoff** (:meth:`Watchdog.admission_allowed`): when
  lanes are exhausted with queries waiting, re-admission attempts back
  off exponentially (``backoff_start`` doubling to ``backoff_max``
  boundaries) instead of retrying every boundary — degraded mode with
  bounded churn, recorded as episodes the doctor's
  ``degraded_mode_bounded`` check judges.

Every action lands in :meth:`Watchdog.block` — the ``watchdog``
sub-block of ``flow-updating-recovery-report/v1`` manifests
(obs/health.check_recovery: ``quarantine_mass``,
``degraded_mode_bounded``).
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """Detection thresholds + backoff policy (module docstring)."""

    diverge_factor: float = 1e6
    stall_boundaries: int = 0          # 0 = stall detection off
    stall_min_drop: float = 0.05       # fractional spread improvement
    backoff_start: int = 1             # boundaries between retries
    backoff_max: int = 16

    def to_jsonable(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_jsonable(cls, doc: dict) -> WatchdogConfig:
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (doc or {}).items()
                      if k in fields})


class Watchdog:
    def __init__(self, config: WatchdogConfig | None = None):
        self.config = config or WatchdogConfig()
        self.actions: list = []        # one record per quarantine
        self.degraded: list = []       # lane-exhaustion episodes
        self.deferred_admissions = 0
        self._episode = None           # open degraded episode
        self._backoff = self.config.backoff_start
        self._skip = 0
        self._lane_trend: dict = {}    # lane -> (boundaries, ref_spread)

    # ---- detection -------------------------------------------------------
    def _verdict(self, q: dict, mx: float, mn: float,
                 resid: float) -> tuple | None:
        """(reason, evidence) for one active lane, or None (healthy)."""
        if not (math.isfinite(mx) and math.isfinite(mn)
                and math.isfinite(resid)):
            return "nan", {"max": repr(mx), "min": repr(mn),
                           "resid": repr(resid)}
        scale = max(abs(mx), abs(mn))
        # aggregate lanes (aggregates/) declare their kind's own healthy
        # scale — a max-consensus lane legitimately sits AT its input
        # extremum forever, a quantile bracket at 1.0 — so the
        # divergence reference prefers kind_scale over the generic
        # value_scale when the kind recorded one
        ref = max(1.0, float(q.get("kind_scale",
                                   q.get("value_scale", 1.0))))
        if scale > self.config.diverge_factor * ref:
            return "divergence", {"estimate_scale": scale,
                                  "value_scale": ref,
                                  "factor": self.config.diverge_factor}
        return None

    def _stalled(self, lane: int, q: dict, spread: float,
                 scale: float) -> dict | None:
        k = self.config.stall_boundaries
        if k <= 0:
            return None
        boundaries, ref = self._lane_trend.get(lane, (0, spread))
        boundaries += 1
        if boundaries >= k:
            drop = 1.0 - spread / ref if ref > 0 else 0.0
            self._lane_trend[lane] = (0, spread)   # window restarts
            if (drop < self.config.stall_min_drop
                    and spread > q["eps"] * max(1.0, scale)):
                return {"spread": spread, "ref_spread": ref,
                        "drop_fraction": drop, "boundaries": k}
        else:
            self._lane_trend[lane] = (boundaries, ref)
        return None

    def inspect(self, fab, probe: dict) -> list:
        """Scan the boundary probe; quarantine pathological lanes via
        the fabric's scrub machinery.  Returns the quarantined lane ids
        (callers re-probe when non-empty — the planes changed)."""
        items = []
        for lane, qid in enumerate(fab._lane_q):
            if qid is None:
                continue
            q = fab._queries[qid]
            mx = float(probe["max"][lane])
            mn = float(probe["min"][lane])
            resid = float(probe["resid"][lane])
            bad = self._verdict(q, mx, mn, resid)
            if bad is None:
                stall = self._stalled(lane, q, mx - mn,
                                      max(abs(mx), abs(mn)))
                if stall is not None:
                    bad = ("stall", stall)
            if bad is not None:
                items.append((lane, qid) + bad)
                self._lane_trend.pop(lane, None)
        if items:
            self.actions.extend(fab._quarantine(items))
        return [lane for lane, *_ in items]

    # ---- admission backoff ----------------------------------------------
    def admission_allowed(self, fab) -> bool:
        """The pre-admission gate, one call per segment boundary.  In a
        lane-exhaustion episode admissions run every ``backoff``
        boundaries (doubling, capped); outside one they run every
        boundary."""
        exhausted = fab.queued > 0 and not fab._free_lanes
        if exhausted and self._episode is None:
            self._episode = {"start_t": fab.clock, "end_t": None,
                             "boundaries": 0, "max_backoff": 0,
                             "peak_queued": fab.queued}
            self.degraded.append(self._episode)
            self._backoff = self.config.backoff_start
            self._skip = 0
        ep = self._episode
        if ep is None:
            return True
        ep["boundaries"] += 1
        ep["peak_queued"] = max(ep["peak_queued"], fab.queued)
        if not (fab._free_lanes and fab._queue):
            return True          # nothing to admit; no retry consumed
        if self._skip > 0:
            self._skip -= 1
            self.deferred_admissions += 1
            return False
        self._skip = self._backoff
        ep["max_backoff"] = max(ep["max_backoff"], self._backoff)
        self._backoff = min(2 * self._backoff, self.config.backoff_max)
        return True

    def after_admission(self, fab) -> None:
        """Close the degraded episode once the queue drains."""
        if self._episode is not None and fab.queued == 0:
            self._episode["end_t"] = fab.clock
            self._episode = None
            self._backoff = self.config.backoff_start
            self._skip = 0

    # ---- checkpointing ---------------------------------------------------
    # The backoff counters, the open degraded episode and the per-lane
    # stall windows are part of the ADMISSION SCHEDULE: a recovery that
    # re-attached a blank watchdog would admit queued queries at
    # different boundaries than the uninterrupted run, breaking the
    # bit-exact replay guarantee.  They ride the ring checkpoints.

    def state_dict(self) -> dict:
        open_idx = (self.degraded.index(self._episode)
                    if self._episode is not None else None)
        return {
            "actions": [dict(a) for a in self.actions],
            "degraded": [dict(d) for d in self.degraded],
            "deferred_admissions": self.deferred_admissions,
            "open_episode": open_idx,
            "backoff": self._backoff,
            "skip": self._skip,
            "lane_trend": {str(k): [int(v[0]), float(v[1])]
                           for k, v in self._lane_trend.items()},
        }

    def load_state(self, doc: dict) -> None:
        self.actions = [dict(a) for a in doc.get("actions", [])]
        self.degraded = [dict(d) for d in doc.get("degraded", [])]
        self.deferred_admissions = int(
            doc.get("deferred_admissions", 0))
        idx = doc.get("open_episode")
        self._episode = self.degraded[idx] if idx is not None else None
        self._backoff = int(doc.get("backoff",
                                    self.config.backoff_start))
        self._skip = int(doc.get("skip", 0))
        self._lane_trend = {int(k): (int(v[0]), float(v[1]))
                            for k, v in
                            doc.get("lane_trend", {}).items()}

    # ---- manifest --------------------------------------------------------
    def block(self) -> dict:
        """The ``watchdog`` sub-block of recovery manifests."""
        return {
            "config": self.config.to_jsonable(),
            "quarantined_total": len(self.actions),
            "actions": [dict(a) for a in self.actions],
            "degraded": [dict(d) for d in self.degraded],
            "deferred_admissions": self.deferred_admissions,
        }
