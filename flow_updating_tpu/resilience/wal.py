"""Append-only event WAL: every service/fabric event journaled before
it is applied.

The streaming engines (service/ServiceEngine, query/QueryFabric) apply
membership and query events as O(event) device edits between compiled
scan segments — deterministic given the pre-event state.  That makes
crash recovery a *replay* problem: restore the newest valid checkpoint
and re-apply the journaled events after it, and the result is bit-exact
vs the uninterrupted run (tests/test_resilience.py pins this, the chaos
harness proves it under real SIGKILL).

Format (one ``wal.log`` per durability directory):

* an 8-byte file magic (:data:`MAGIC`), then records back to back;
* each record is ``<u32 length> <u32 crc32(payload)> <payload>``
  (little-endian), payload = compact JSON of
  ``{"seq", "t", "kind", "args"}`` — ``seq`` is the 1-based monotonic
  record number, ``t`` the engine clock when the event was journaled;
* every append is flushed and ``fsync``'d before the event is applied
  (write-ahead: a crash between journal and apply re-applies on
  recovery, which is what the caller asked for);
* a **torn tail** — the partial record a crash mid-append leaves — is
  detected by the length/CRC frame and truncated cleanly on open: the
  journal never propagates garbage, it only loses the one event that
  was never acknowledged.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib

MAGIC = b"FUWAL001"
_HEADER = struct.Struct("<II")   # (payload length, crc32)

#: Cap on a single record's payload — a frame whose length field exceeds
#: this is corruption (or not a WAL at all), not a huge event.
MAX_RECORD_BYTES = 64 * 1024 * 1024


def scan_wal(path: str) -> tuple[list, int]:
    """Read every intact record of a WAL file.  Returns
    ``(records, torn_bytes)`` — ``torn_bytes`` is the size of the
    trailing partial/corrupt frame a crash left (0 on a clean file).
    A missing file reads as empty; a file without the magic is not a
    WAL and raises ValueError naming it."""
    if not os.path.exists(path):
        return [], 0
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(MAGIC) or blob[:len(MAGIC)] != MAGIC:
        raise ValueError(
            f"wal {path}: missing file magic — not a flow_updating_tpu "
            "event WAL (or the file was overwritten)")
    records = []
    off = len(MAGIC)
    while off < len(blob):
        if off + _HEADER.size > len(blob):
            break                              # torn mid-header
        length, crc = _HEADER.unpack_from(blob, off)
        start, end = off + _HEADER.size, off + _HEADER.size + length
        if length > MAX_RECORD_BYTES or end > len(blob):
            break                              # torn mid-payload
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            break                              # corrupt frame
        try:
            records.append(json.loads(payload.decode()))
        except (ValueError, UnicodeDecodeError):
            break
        off = end
    return records, len(blob) - off


class WriteAheadLog:
    """One durability directory's journal (module docstring).

    Opening an existing file scans it, truncates any torn tail in
    place, and continues appending after the last intact record — the
    sequence numbers stay monotonic across process restarts."""

    def __init__(self, path: str, *, fsync: bool = True,
                 keep_records: bool = False):
        self.path = path
        self.fsync = bool(fsync)
        self.torn_bytes = 0
        #: Wall-time accounting for the serving metrics plane
        #: (obs/metrics.py samples these at segment boundaries): total
        #: appends this process, total/last flush+fsync seconds.
        self.appends_total = 0
        self.fsync_seconds_total = 0.0
        self.last_fsync_s = 0.0
        #: The intact records found at open — populated only under
        #: ``keep_records`` (recovery replays them; a plain writer has
        #: no reason to hold the whole journal in memory).
        self.records: list | None = None
        if os.path.exists(path):
            records, torn = scan_wal(path)
            self.last_seq = int(records[-1]["seq"]) if records else 0
            self.records_on_open = len(records)
            if keep_records:
                self.records = records
            if torn:
                # truncate the torn tail so the file is clean for the
                # next reader (the lost record was never acknowledged)
                keep = os.path.getsize(path) - torn
                with open(path, "r+b") as f:
                    f.truncate(keep)
                    f.flush()
                    os.fsync(f.fileno())
                self.torn_bytes = torn
        else:
            with open(path, "wb") as f:
                f.write(MAGIC)
                f.flush()
                os.fsync(f.fileno())
            self.last_seq = 0
            self.records_on_open = 0
            if keep_records:
                self.records = []
        self._f = open(path, "ab")

    def append(self, kind: str, args: dict, t: int) -> int:
        """Journal one event; returns its sequence number.  The record
        is on disk (fsync'd) when this returns — callers apply the
        event only after."""
        seq = self.last_seq + 1
        payload = json.dumps(
            {"seq": seq, "t": int(t), "kind": kind, "args": args},
            separators=(",", ":")).encode()
        self._f.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        t0 = time.perf_counter()
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self.last_fsync_s = time.perf_counter() - t0
        self.fsync_seconds_total += self.last_fsync_s
        self.appends_total += 1
        self.last_seq = seq
        return seq

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def block(self) -> dict:
        """The manifest's ``wal`` sub-block (obs/report.py)."""
        return {
            "path": os.path.basename(self.path),
            "last_seq": self.last_seq,
            "torn_bytes_truncated": self.torn_bytes,
            "fsync": self.fsync,
            "appends_total": self.appends_total,
            "fsync_seconds_total": self.fsync_seconds_total,
        }
