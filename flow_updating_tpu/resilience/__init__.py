"""Crash-safe serving: event WAL, checkpoint ring, lane watchdog, chaos.

The protocol layer already self-heals (the paper's churn tolerance,
PR-9's adversarial registry); this package gives the long-running
engines the *infrastructure*-layer fault tolerance a production service
needs — and the chaos harness that proves it, fault by planted fault:

* :mod:`~flow_updating_tpu.resilience.wal` — append-only, CRC-framed,
  fsync'd event journal; a torn tail truncates cleanly;
* :mod:`~flow_updating_tpu.resilience.ring` — automatic checkpoint
  ring (every K segments, N retained, atomic writes, integrity
  sidecars, corrupt-newest falls back to next);
* :mod:`~flow_updating_tpu.resilience.recover` — arm durability on a
  live engine; rebuild one from its directory by checkpoint restore +
  WAL replay (bit-exact vs the uninterrupted run);
* :mod:`~flow_updating_tpu.resilience.watchdog` — inline per-lane
  NaN/divergence/stall detection riding the existing lane probe, with
  mass-neutral lane quarantine and admission backoff;
* :mod:`~flow_updating_tpu.resilience.chaos` — the infra-fault
  registry (kill, torn WAL, corrupt/bitflipped archives, NaN poison,
  admission storm), each injected into a real subprocess run with its
  recovery signature doctor-asserted and ``inspect --blame`` naming
  the planted fault.

Surface: ``ServiceEngine.enable_durability`` / ``.recover``,
``QueryFabric.enable_durability`` / ``.attach_watchdog`` / ``.recover``,
the ``chaos`` CLI subcommand, ``serve``/``query`` ``--wal`` flags, and
``flow-updating-recovery-report/v1`` manifests judged by
``obs.health.check_recovery``.  See docs/RESILIENCE.md.
"""

from flow_updating_tpu.resilience.recover import arm_durability, recover
from flow_updating_tpu.resilience.ring import CheckpointRing
from flow_updating_tpu.resilience.wal import WriteAheadLog, scan_wal
from flow_updating_tpu.resilience.watchdog import Watchdog, WatchdogConfig

__all__ = [
    "CheckpointRing",
    "Watchdog",
    "WatchdogConfig",
    "WriteAheadLog",
    "arm_durability",
    "recover",
    "scan_wal",
]
