"""Engine façade — the S4U-shaped driver API over the vectorized kernel.

Mirrors the verbs a user of the reference touches (SURVEY.md N1/A10; the
reference's ``__main__`` at ``flowupdating-collectall.py:151-166``):
``Engine(argv)`` -> ``load_platform`` -> ``register_actor`` ->
``load_deployment`` -> ``netzone_root.add_host`` -> ``run_until`` — plus
``Engine.clock``, the watcher, and ``global_values``-style readback.  Under
the hood there are no actors or mailboxes: the deployment resolves to a
:class:`Topology`, state is one pytree, and ``run_until`` advances it in
compiled chunks of rounds, surfacing to the host only at watcher sampling
points (the reference's every-10-sim-seconds dump,
``collectall.py:139-142``).

Simulated-time convention: one round == ``TICK_INTERVAL`` (1.0) simulated
seconds, the reference peers' loop cadence.
"""

from __future__ import annotations

import logging
from collections.abc import Callable

import numpy as np

from flow_updating_tpu.models.config import RoundConfig
from flow_updating_tpu.models.rounds import (
    node_estimates,
    run_rounds,
    run_rounds_streamed,
)
from flow_updating_tpu.models.state import FlowUpdatingState, init_state
from flow_updating_tpu.topology.deployment import Deployment, load_deployment
from flow_updating_tpu.topology.graph import Topology
from flow_updating_tpu.topology.platform import Platform, load_platform

logger = logging.getLogger("flow_updating_tpu.engine")

TICK_INTERVAL = 1.0  # simulated seconds per round


def _aot_timed(runner, state, arrays, *, cfg, num_rounds, spec, true_mean,
               **static_kw):
    """Run a jitted telemetry runner with the compile wall time measured
    separately via AOT lowering (``.lower().compile()``); falls back to a
    plain call (compile time folded into execution) when the runner or
    backend does not support AOT.  Returns ``(state, series, compile_s)``.
    Extra keyword arguments must be static argnames of the runner (they
    are omitted from the compiled call).
    """
    import time as _time

    try:
        lowered = runner.lower(state, arrays, cfg, num_rounds, spec,
                               true_mean, **static_kw)
        t0 = _time.perf_counter()
        compiled = lowered.compile()
        compile_s = _time.perf_counter() - t0
    except (AttributeError, TypeError, NotImplementedError):
        out_state, series = runner(state, arrays, cfg, num_rounds, spec,
                                   true_mean, **static_kw)
        return out_state, series, None
    # the compiled call stays OUTSIDE the fallback: an execution-time
    # error must surface, not silently re-run the whole scan
    out_state, series = compiled(state, arrays, true_mean)
    return out_state, series, compile_s


def _log_stream_sample(m: dict) -> None:
    logger.info(
        "[%d] rmse=%.3e max_err=%.3e mass=%.6g fired=%d",
        m["t"], m["rmse"], m["max_abs_err"], m["mass"], m["fired_total"],
    )


class _NetzoneShim:
    """Compatibility shim for ``e.netzone_root.add_host(name, speed)``
    (reference ``flowupdating-collectall.py:159``).  Hosts added here that
    never receive a peer (like the reference's ``observer``) simply don't
    join the gossip graph."""

    def __init__(self, engine: Engine):
        self._engine = engine

    def add_host(self, name: str, speed: float):
        if self._engine.platform is None:
            self._engine.platform = Platform(hosts={}, links={}, routes={})
        self._engine.platform = self._engine.platform.add_host(name, speed)
        return name


class Engine:
    """Driver for one simulation/aggregation run."""

    def __init__(self, argv=None, config: RoundConfig | None = None,
                 mesh=None, multichip: str = "auto",
                 halo: str = "ppermute", partition: str = "bfs",
                 host_actors: bool = False, event_log=None,
                 plan="off", adversary=None):
        # argv passthrough mirrors ``Engine(sys.argv)``; recognized flags are
        # consumed by the CLI layer (flow_updating_tpu.cli) — the Engine
        # accepts a ready RoundConfig here.  ``mesh`` (a jax.sharding.Mesh
        # over the 'nodes' axis) turns on multi-chip execution.
        #
        # ``multichip`` selects the distribution strategy under a mesh:
        #   'auto' — GSPMD (annotate shardings, XLA places collectives);
        #            the node kernel with spmv='benes_fused' uses the
        #            shard_map fused-circuit kernel.
        #   'halo' — the explicitly scheduled shard_map halo-exchange
        #            kernel (parallel/sharded.py): edges live with their
        #            source shard, only cut-edge payloads cross chips
        #            (``halo``: 'ppermute' point-to-point, 'allgather'
        #            broadcast, 'overlap' interior/frontier-split
        #            schedule that hides the wire behind interior
        #            compute [bit-exact vs ppermute], 'overlap_pallas'
        #            the same schedule with the Pallas async-remote-copy
        #            kernel carrying the wire, or 'auto' — ranked from
        #            the plan's measured cut-edge bytes
        #            [plan.select.select_halo_mode, recorded in
        #            halo_report()]; ``partition``: 'bfs'/'contiguous').
        #   'pod'  — the pod-sharded fat-tree stencil
        #            (parallel/structured_sharded.py): node kernel,
        #            spmv='structured', fat-tree topologies with S | k;
        #            one (k/2,)-element psum per round.
        # ``plan`` turns on the topology compiler (flow_updating_tpu.plan):
        #   'off'  — historical dispatch, exactly the configured flags.
        #   'auto' — after the topology resolves, pick the fastest correct
        #            kernel/spmv for (topology, backend): the structured
        #            stencil on generator-regular graphs, the compiled
        #            RCM-band + Benes/gather-remainder plan or the generic
        #            layouts on arbitrary graphs (plan/select.py).  Only
        #            ever changes WHICH implementation of the requested
        #            dynamics runs, never the dynamics themselves.
        #   an ExecutionPlan / PlanDecision instance — use it as-is.
        if multichip not in ("auto", "halo", "pod"):
            raise ValueError(f"unknown multichip mode {multichip!r}")
        if halo not in ("ppermute", "allgather", "overlap",
                        "overlap_pallas", "auto"):
            raise ValueError(
                f"unknown halo mode {halo!r}: use 'ppermute', "
                "'allgather', 'overlap', 'overlap_pallas', or 'auto'")
        if isinstance(plan, str):
            if plan not in ("off", "auto"):
                raise ValueError(
                    f"unknown plan mode {plan!r}: use 'off', 'auto', or "
                    "pass a compiled flow_updating_tpu.plan "
                    "ExecutionPlan / PlanDecision")
        elif plan is not None:
            from flow_updating_tpu.plan import ExecutionPlan
            from flow_updating_tpu.plan.select import PlanDecision

            if not isinstance(plan, (ExecutionPlan, PlanDecision)):
                # a dict/describe() output/bool must not silently run
                # auto-selection in place of the caller's intended plan
                raise TypeError(
                    f"plan= takes 'off', 'auto', an ExecutionPlan or a "
                    f"PlanDecision; got {type(plan).__name__}")
        # ``adversary`` (a flow_updating_tpu.scenarios Adversary, or any
        # object with device_leaves()/describe()) plants device-side
        # Byzantine faults on the message wire: value lies, flow
        # corruption, silent drops, scheduled correlated link failure
        # (models/rounds.py).  Single-device edge kernel only — the
        # injection lives in the per-edge fire/send path.
        self.adversary = adversary or None
        self.argv = list(argv) if argv else []
        self.config = config or RoundConfig.fast()
        self.config = self._apply_argv_cfg(self.config)
        self.mesh = mesh
        self.multichip = multichip
        self.halo = halo
        self.partition = partition
        self.platform: Platform | None = None
        self.deployment: Deployment | None = None
        self.topology: Topology | None = None
        self.state: FlowUpdatingState | None = None
        self._registered: dict = {}
        self._watchers: list = []
        self._clock = 0.0
        self._killed = False
        self._n_real: int | None = None   # real node count when mesh-padded
        self._halo_plan = None
        self._halo_resolved = None  # halo='auto' resolution (set at build)
        self.halo_decision = None   # select_halo_mode evidence when 'auto'
        self.plan_spec = plan
        self.plan_decision = None   # PlanDecision once build() resolved it
        self._plan = None           # ExecutionPlan handed to the NodeKernel
        self._fused_kw = None       # autotuned fused-round knobs
        #                             (tile / remainder route)
        self.netzone_root = _NetzoneShim(self)
        # optional EventLog sink for engine lifecycle records ("advance"
        # compiled-chunk dispatches, "kill_all") — together with the s4u
        # actor/comm events the raw material of `obs export-trace`
        self.event_log = event_log
        # compile/execute wall-time split of the last run_telemetry call
        # (run manifests record it); None entries = not measured
        self.telemetry_timings: dict = {}
        # host-fidelity mode: arbitrary Python actors on the s4u host DES
        # (flow_updating_tpu.s4u) instead of array kernels — the explicit
        # opt-in for the reference's register_actor(<any class>) surface
        self.host_actors = bool(host_actors)
        self._hostdes = None
        if self.host_actors and mesh is not None:
            raise ValueError(
                "host_actors=True runs Python bytecode on the host; it "
                "cannot shard over a device mesh — drop mesh=, or "
                "express the protocol as a VectorActor")

    def _apply_argv_cfg(self, cfg: RoundConfig) -> RoundConfig:
        """Consume SimGrid-style ``--cfg=key:value`` flags from argv.

        The reference passes ``sys.argv`` straight into the engine and
        SimGrid interprets ``--cfg=`` entries as config overrides
        (``flowupdating-collectall.py:152``).  Here every RoundConfig
        field is addressable by its name (dashes accepted for
        underscores); values are parsed to the field's type and the
        result re-validated by RoundConfig itself.  SimGrid's own
        ``category/name`` keys (always slash-form, e.g. ``network/model``)
        have no equivalent on this runtime and are logged + skipped so a
        reference command line keeps working verbatim; a mistyped *bare*
        key raises, like SimGrid's xbt error — silently ignoring a config
        override is worse than failing."""
        import dataclasses as _dc
        import typing as _t

        # PEP 563: field.type is a string under `from __future__ import
        # annotations`; resolve the declared types, not the live values'
        hints = _t.get_type_hints(RoundConfig)
        overrides = {}
        for arg in self.argv:
            if not (isinstance(arg, str) and arg.startswith("--cfg=")):
                continue
            key, sep, val = arg[len("--cfg="):].partition(":")
            key = key.strip()
            if "/" in key:
                logger.warning(
                    "--cfg=%s: SimGrid engine key has no equivalent on "
                    "the TPU runtime; ignored", key)
                continue
            key = key.replace("-", "_")
            if not sep and key in hints:
                # a valid knob missing its value is a syntax slip, not an
                # unknown key — diagnose the actual mistake (ADVICE r5 #2)
                raise ValueError(
                    f"--cfg={key}: missing ':' separator "
                    f"(format --cfg={key}:value)")
            if key not in hints:
                raise ValueError(
                    f"--cfg={key!r}: unknown config key (valid: "
                    f"{', '.join(sorted(hints))}; format "
                    "--cfg=key:value)")
            ftype = hints[key]
            if ftype is bool:
                low = val.strip().lower()
                if low in ("1", "true", "yes", "on"):
                    overrides[key] = True
                elif low in ("0", "false", "no", "off"):
                    overrides[key] = False
                else:
                    raise ValueError(
                        f"--cfg={key}:{val!r}: not a boolean "
                        "(use true/false, yes/no, on/off, 1/0)")
            elif ftype in (int, float):
                try:
                    overrides[key] = ftype(val)
                except ValueError:
                    # name the offending flag, not just int()'s bare
                    # "invalid literal" (ADVICE r5 #2)
                    raise ValueError(
                        f"--cfg={key}:{val}: not a valid "
                        f"{ftype.__name__} value") from None
            else:
                overrides[key] = val.strip()
        return _dc.replace(cfg, **overrides) if overrides else cfg

    # ---- setup -----------------------------------------------------------
    @property
    def clock(self) -> float:
        return self._clock

    def load_platform(self, path: str) -> Engine:
        self.platform = load_platform(path)
        return self

    def register_actor(self, name: str, fn=None) -> Engine:
        """Register a deployable actor.

        ``fn=None`` selects the built-in gossip protocols (variant via
        ``RoundConfig.variant``) — the reference's
        ``register_actor("peer", Peer)`` maps to this plus config.

        ``fn`` may also be a :class:`~flow_updating_tpu.models.actor.
        VectorActor`: the vetted extension point for custom protocols,
        written as pure population-wide array functions and scanned
        under ``jit`` like the built-in kernels (see ``models/actor.py``
        for the contract and the per-actor-class rationale).

        With ``Engine(host_actors=True)``, ``fn`` may be ANY Python
        callable/class — the reference's ``register_actor("peer", Peer)``
        surface (``flowupdating-collectall.py:156``) — executed on the
        deterministic host-side DES (:mod:`flow_updating_tpu.s4u`) at
        host speed.  Without that opt-in, anything else raises:
        per-actor Python bytecode cannot execute on a TPU, and silently
        recording it would make users think their callable runs."""
        if self.host_actors:
            if fn is not None and not callable(fn):
                raise TypeError(
                    f"register_actor({name!r}): {type(fn).__name__} is "
                    "not callable")
            self._registered[name] = fn
            return self
        from flow_updating_tpu.models.actor import VectorActor

        if fn is not None and not isinstance(fn, VectorActor):
            raise TypeError(
                f"register_actor({name!r}): got {type(fn).__name__}; "
                "per-actor Python callables cannot execute on TPU.  Pass "
                "None to select the built-in protocols "
                "(RoundConfig.variant), express the protocol as a "
                "flow_updating_tpu.models.actor.VectorActor — pure "
                "(N,)/(E,) array functions scanned under jit — or opt "
                "into the host-fidelity runtime with "
                "Engine(host_actors=True) to run arbitrary Python "
                "actors on the s4u host DES (reference semantics, host "
                "speed, not TPU)"
            )
        self._registered[name] = fn
        return self

    @property
    def _custom_actor(self):
        for fn in self._registered.values():
            if fn is not None:
                return fn
        return None

    @property
    def _halo_mode(self) -> bool:
        return self.mesh is not None and self.multichip == "halo" \
            and self._custom_actor is None

    @property
    def _ledger_dtype_bytes(self) -> int:
        """Bytes per ledger element on the halo wire (the flow/estimate
        payload dtype) — the ONE accounting shared by the halo='auto'
        ranking and halo_report()'s evidence, so the recorded decision
        evidence can never use different byte counts than the decision
        itself."""
        return 8 if self.config.dtype == "float64" else 4

    @property
    def _halo_wire(self) -> str:
        """The concrete exchange mode the halo kernel dispatches with
        (``halo='auto'`` resolves at build from the plan's measured
        cut-edge bytes; before build, the serialized default)."""
        if self._halo_resolved is not None:
            return self._halo_resolved
        return "ppermute" if self.halo == "auto" else self.halo

    def halo_report(self) -> dict | None:
        """JSON-ready record of the halo exchange decision: the
        requested and resolved modes, the schedule the program actually
        executes (``'overlap'`` may rewrite to ``'overlap_full'`` at
        plan time on fat frontiers), plus ``select_halo_mode``'s
        evidence when 'auto' did the choosing.  None off the halo
        path."""
        if not self._halo_mode or self._halo_plan is None:
            return None
        from flow_updating_tpu.parallel import overlap as _ovl

        out = {"requested": self.halo, "resolved": self._halo_wire,
               "schedule": _ovl.resolve_mode(self._halo_plan,
                                             self._halo_wire),
               "partition": self.partition,
               **self._halo_plan.collective_bytes_per_round(
                   self._ledger_dtype_bytes)}
        if self.halo_decision is not None:
            out["decision"] = self.halo_decision
        return out

    @property
    def _pod_mode(self) -> bool:
        return self.mesh is not None and self.multichip == "pod" \
            and self._custom_actor is None

    @property
    def _node_like(self) -> bool:
        """Dispatch through the node-kernel interface (built-in
        node-collapsed kernel, or an ActorKernel driving a VectorActor)."""
        return self.config.kernel == "node" or self._custom_actor is not None

    @property
    def _kernel_kind(self) -> str:
        """The kernel dispatch mode: 'edge' (single-device and GSPMD),
        'node', 'halo', or 'pod' — the key telemetry support and cost
        attribution dispatch on."""
        return ("halo" if self._halo_mode else
                "pod" if self._pod_mode else
                "node" if self._node_like else "edge")

    def load_deployment(self, path: str, function: str | None = None) -> Engine:
        if function is None and len(self._registered) == 1:
            function = next(iter(self._registered))
        self.deployment = load_deployment(path, function=function)
        if self.host_actors:
            # spawn reference-style now, so a driver-level Actor.create
            # (e.g. the watcher, collectall.py:162) finds a live runtime
            # between load_deployment and run_until
            self._host_spawn_deployment()
        return self

    def _host_des(self):
        """The lazily created s4u host DES (host_actors mode)."""
        from flow_updating_tpu import s4u

        if self._hostdes is None:
            self._hostdes = s4u.HostDes(platform=self.platform,
                                        event_log=self.event_log)
            s4u._CURRENT_DES = self._hostdes
        return self._hostdes

    def _host_spawn_deployment(self) -> None:
        """Instantiate each deployment actor SimGrid-style: the
        registered class is constructed with the deployment's string
        args *inside the actor context* and then called
        (``flowupdating-collectall.py:156-157`` + ``actors.xml:4-7``)."""
        des = self._host_des()
        for spec in self.deployment.actors:
            fn = self._registered.get(spec.function)
            if fn is None:
                raise RuntimeError(
                    f"deployment binds function {spec.function!r} but no "
                    "callable was registered for it (host_actors mode "
                    "has no built-in protocol fallback)")
            des.spawn(spec.host, des.host(spec.host),
                      lambda _f=fn, _a=spec.args: _f(*_a)(), ())

    def set_topology(self, topo: Topology) -> Engine:
        self.topology = topo
        return self

    def _resolve_topology(self, latency_scale: float = 0.0) -> Engine:
        if self.topology is None:
            if self.deployment is None:
                raise RuntimeError("no deployment loaded and no topology set")
            self.topology = self.deployment.to_topology(
                platform=self.platform,
                tick_interval=TICK_INTERVAL,
                latency_scale=latency_scale,
            )
        return self

    def _prepare_arrays(self, latency_scale: float = 0.0) -> None:
        """Device arrays for the configured kernel (no fresh state)."""
        if self.adversary is not None:
            if (self.mesh is not None or self.host_actors
                    or self._custom_actor is not None):
                raise ValueError(
                    "adversary= injects faults into the single-device "
                    "edge kernel's wire; multi-chip / host-actor / "
                    "custom-actor dispatch is not covered — drop mesh=/"
                    "host_actors=, or run the scenario under the sweep "
                    "engine (SweepInstance.adversary)")
            if self.config.kernel != "edge":
                raise ValueError(
                    "adversary= corrupts per-edge wire state; the node-"
                    "collapsed kernel has no wire — use kernel='edge'")
            if self.config.needs_coloring:
                raise ValueError(
                    "adversary= targets the message-based protocols; the "
                    "fast synchronous pairwise mode exchanges estimates "
                    "directly on-chip (no wire to attack) — use "
                    "variant='collectall' or fire_policy='reference'")
        if self._custom_actor is not None:
            from flow_updating_tpu.models.actor import ActorKernel

            if self.mesh is not None and self.multichip in ("halo", "pod"):
                raise ValueError(
                    f"multichip={self.multichip!r} drives a built-in "
                    "kernel; custom VectorActors distribute via GSPMD — "
                    "use multichip='auto'")
            if latency_scale > 0.0 or self.topology.max_delay > 1:
                raise ValueError(
                    "VectorActor rounds are unit-delay synchronous; "
                    "latency-warped delivery applies to the built-in "
                    "edge kernel only")
            self._node_kernel = ActorKernel(self.topology,
                                            self._custom_actor,
                                            mesh=self.mesh)
            self._topo_arrays = None
            return
        if self._halo_mode:
            if self.config.kernel == "node":
                raise ValueError(
                    "multichip='halo' drives the edge kernel "
                    "(per-edge state partitioned by source shard); the "
                    "node kernel distributes via GSPMD or the sharded "
                    "fused-circuit kernel — use multichip='auto'"
                )
            if latency_scale > 0.0 or self.config.contention:
                raise NotImplementedError(
                    "the halo kernel runs unit-delay/static-delay rounds; "
                    "latency-warped + contention fidelity runs are "
                    "single-device (platform-scale)"
                )
            from flow_updating_tpu.parallel import sharded

            self._halo_plan = sharded.plan_sharding(
                self.topology, self.mesh.devices.size,
                partition=self.partition,
                coloring=self.config.needs_coloring,
            )
            if self.halo == "auto":
                from flow_updating_tpu.plan.select import select_halo_mode

                self.halo_decision = select_halo_mode(
                    self._halo_plan,
                    dtype_bytes=self._ledger_dtype_bytes)
                self._halo_resolved = self.halo_decision["halo"]
                logger.info("halo auto: %s", self.halo_decision["reason"])
            else:
                self._halo_resolved = self.halo
            self._halo_arrays = sharded.plan_device_arrays(
                self._halo_plan, self.mesh,
                # the overlap split tables are built only when the
                # resolved wire dispatches through them
                halo=self._halo_resolved)
            self._topo_arrays = None
            return
        if self.config.kernel == "node":
            if latency_scale > 0.0 or self.topology.max_delay > 1:
                raise ValueError(
                    "latency-warped rounds need per-edge delivery state; "
                    "the node-collapsed kernel is unit-delay only — use "
                    "kernel='edge' with latency_scale"
                )
            from flow_updating_tpu.models import sync

            if self._pod_mode:
                from flow_updating_tpu.parallel.structured_sharded import (
                    PodShardedFatTreeKernel,
                )

                if self.config.spmv != "structured":
                    raise ValueError(
                        "multichip='pod' runs the pod-sharded stencil; "
                        "it requires spmv='structured'"
                    )
                self._node_kernel = PodShardedFatTreeKernel(
                    self.topology, self.config, self.mesh,
                    # the pod stencil's overlap schedule is the same
                    # math reordered (early psum, core last): free to
                    # take whenever overlap is requested or auto-picked
                    overlap=self.halo in ("overlap", "overlap_pallas",
                                          "auto"),
                )
            elif self.mesh is not None and self.config.spmv == "benes_fused":
                from flow_updating_tpu.parallel.spmv_sharded import (
                    ShardedNodeKernel,
                )

                self._node_kernel = ShardedNodeKernel(
                    self.topology, self.config, self.mesh
                )
            elif self.mesh is not None and \
                    self.config.spmv == "banded_fused":
                from flow_updating_tpu.parallel.banded_sharded import (
                    ShardedBandedKernel,
                )

                # halo='ppermute' keeps the serialized XLA oracle; every
                # other wire setting takes the one-kernel-per-shard
                # remote-DMA form (interpret mode off-TPU)
                self._node_kernel = ShardedBandedKernel(
                    self.topology, self.config, self.mesh,
                    plan=self._plan,
                    exchange="ppermute" if self.halo == "ppermute"
                    else "pallas",
                )
            else:
                self._node_kernel = sync.NodeKernel(
                    self.topology, self.config, mesh=self.mesh,
                    plan=self._plan, **(self._fused_kw or {}),
                )
            self._topo_arrays = None
            return
        if self._pod_mode:
            raise ValueError(
                "multichip='pod' drives the node kernel "
                "(kernel='node', spmv='structured')"
            )
        if latency_scale > 0.0:
            depth = max(self.config.delay_depth, self.topology.max_delay)
            if depth != self.config.delay_depth:
                import dataclasses

                self.config = dataclasses.replace(self.config, delay_depth=depth)
        if self.config.contention:
            if not self.topology.has_link_model:
                raise ValueError(
                    "contention=True needs a platform-loaded topology with "
                    "a link model and a positive latency scale — pass "
                    "--platform with --latency-scale > 0 on the CLI "
                    "(generators have no links)"
                )
            if self.mesh is not None:
                raise NotImplementedError(
                    "contention is single-device (the per-round link flow "
                    "count is a global reduction; fidelity runs are "
                    "platform-scale)"
                )
            # the ring buffer must cover the WORST contended delay, or
            # edge_delays' clamp silently flattens contention back to the
            # static profile
            base = self.topology.contended_max_delay()
            depth = max(self.config.delay_depth, base)
            if self.config.contention_backlog:
                # backlog makes the bound self-referential: up to D
                # standing messages per edge add load, which grows D.
                # Find the smallest self-consistent depth; under overload
                # no finite fixed point exists (congestive collapse), so
                # saturate at 4x the senders-only bound — beyond it the
                # clamp IS the model's queue-capacity limit (delays
                # saturate at delay_depth; the dynamic LMM oracle,
                # native.des_run_contend(lmm=True), is the
                # unbounded-queue tool)
                cap = max(4 * base, depth)
                for _ in range(16):
                    nxt = min(cap, max(depth,
                                       self.topology.contended_max_delay(
                                           inflight_per_edge=depth)))
                    if nxt == depth:
                        break
                    depth = nxt
            if depth != self.config.delay_depth:
                import dataclasses

                self.config = dataclasses.replace(
                    self.config, delay_depth=depth
                )
        if self.mesh is not None:
            if self.config.use_segment_ell or self.config.use_segment_benes:
                raise ValueError(
                    f"segment_impl={self.config.segment_impl!r} is single-"
                    "device only (the layouts index the global edge list); "
                    "with a mesh, GSPMD lowers the segment path's "
                    "collectives instead"
                )
            if self.config.delivery in ("benes", "benes_fused"):
                raise ValueError(
                    f"delivery={self.config.delivery!r} is "
                    "single-device only (the network "
                    "masks index the global edge list); with a mesh, use "
                    "delivery='gather' or the shard_map halo kernel"
                )
            from flow_updating_tpu.parallel import auto

            padded, self._n_real, _ = auto.pad_topology(
                self.topology, self.mesh.devices.size
            )
            self._padded_topology = padded
            self._topo_arrays = None  # built with the state in build()
        else:
            self._topo_arrays = self.topology.device_arrays(
                coloring=self.config.needs_coloring,
                segment_ell=self.config.use_segment_ell,
                segment_benes=self.config.segment_benes_mode,
                delivery_benes=self.config.delivery_benes_mode,
            )
            if self.adversary is not None:
                # plant the device-side fault masks (pytree structure:
                # an absent family stays None and the compiled program
                # is the plain one)
                self._topo_arrays = self._topo_arrays.replace(
                    **self.adversary.device_leaves(
                        self.topology.num_nodes, self.topology.num_edges,
                        self.config.jnp_dtype))

    def _apply_plan(self) -> None:
        """Resolve ``plan=`` into a concrete kernel/spmv choice (the
        topology compiler's auto mode, ROADMAP open item 1).

        Runs between topology resolution and array preparation: the
        decision may rewrite ``self.config``'s kernel/spmv fields — and
        only those; the requested dynamics (variant, fire policy, drop,
        delays) are inputs to the selection, never outputs.  The chosen
        :class:`~flow_updating_tpu.plan.compile.ExecutionPlan` (RCM
        order + band masks + remainder route) is handed to the
        NodeKernel, whose existing permutation machinery keeps every
        readback, telemetry row and field series in ORIGINAL node order.
        """
        if self.plan_spec in (None, "off"):
            return
        if (self.mesh is not None or self.host_actors
                or self._custom_actor is not None):
            logger.info(
                "plan=%r: multi-chip / custom-actor dispatch is not "
                "planned yet; keeping the configured execution mode",
                self.plan_spec)
            return
        from flow_updating_tpu.plan import ExecutionPlan, select_plan
        from flow_updating_tpu.plan.select import PlanDecision

        feats = 0
        vals = self.topology.values
        if vals is not None and getattr(vals, "ndim", 1) > 1:
            feats = int(vals.size // vals.shape[0])
        spec = self.plan_spec
        if isinstance(spec, ExecutionPlan):
            decision = PlanDecision(
                kernel="node", spmv="banded", plan=spec,
                backend="explicit", predicted={},
                reason="explicit ExecutionPlan passed to Engine(plan=)")
        elif isinstance(spec, PlanDecision):
            decision = spec
        else:  # 'auto'
            decision = select_plan(self.topology, self.config,
                                   features=feats)
        if decision.kernel == "node" and not \
                self.config.is_fast_sync_collectall:
            raise ValueError(
                "the supplied plan selects the node kernel, but this "
                "config runs dynamics only the edge kernel implements "
                f"({self.config.variant!r}/{self.config.fire_policy!r}"
                f"/drop={self.config.drop_rate}) — use plan='auto' to "
                "let selection respect the config")
        import dataclasses

        if decision.kernel == "node":
            self.config = dataclasses.replace(
                self.config, kernel="node", spmv=decision.spmv)
            self._plan = decision.plan \
                if decision.spmv in ("banded", "banded_fused") else None
            if decision.spmv == "banded_fused":
                # the autotuner's measured tile / remainder route (or
                # the heuristic defaults when probing was skipped)
                self._fused_kw = dict(
                    (decision.fused or {}).get("chosen")
                    or {"fused_tile": None, "fused_remainder": "auto"})
        else:
            self.config = dataclasses.replace(self.config, kernel="edge")
            self._plan = None
        self.plan_decision = decision
        logger.info("plan: %s", decision.reason)

    def plan_report(self, mixing: bool = False) -> dict | None:
        """JSON-ready record of the plan decision (None when planning
        was off or fell back) — the ``plan`` block of run and plan
        manifests (``flow-updating-plan-report/v1``).  Vector-payload
        engines additionally carry the payload-schedule ranking (the
        chunked-vs-monolithic payload-bytes term of plan='auto',
        plan/select.select_payload_schedule) so manifests record how
        the DFL schedules would rank on this topology/backend.

        ``mixing=True`` additionally estimates the topology's spectral
        gap (obs/spectral.mixing_report — both provenances, persisted
        in the autotune cache) and embeds it as the ``mixing`` block,
        the a-priori convergence budget doctor's ``mixing_sane``
        judges and forecast-aware admission prices against."""
        if self.plan_decision is None:
            return None
        out = self.plan_decision.describe()
        if mixing and self.topology is not None:
            from flow_updating_tpu.obs.spectral import mixing_report

            out["mixing"] = mixing_report(
                self.topology,
                plan=self._plan if self.plan_decision.spmv
                in ("banded", "banded_fused") else None)
        vals = self.topology.values if self.topology is not None else None
        if vals is not None and getattr(vals, "ndim", 1) > 1:
            from flow_updating_tpu.plan.select import (
                select_payload_schedule,
            )

            feats = int(vals.size // vals.shape[0])
            try:
                import jax.numpy as _jnp

                out["payload_schedule"] = select_payload_schedule(
                    self.topology, features=feats,
                    dtype_bytes=_jnp.dtype(
                        self.config.jnp_dtype).itemsize)
            except ValueError as exc:
                out["payload_schedule"] = {"error": str(exc)}
        if getattr(self, "_node_kernel", None) is not None:
            from flow_updating_tpu.obs.profile import fused_round_report

            fused = fused_round_report(self._node_kernel)
            if fused is not None:
                # the one-kernel round's HBM attribution (pass counts,
                # bytes/round) — regress --against gates growth here
                out["fused_round"] = fused
        return out

    def build(self, latency_scale: float = 0.0, seed: int = 0) -> Engine:
        """Resolve deployment(+platform) into topology + fresh state."""
        self._resolve_topology(latency_scale)
        self._apply_plan()
        self._prepare_arrays(latency_scale)
        if self._halo_mode:
            from flow_updating_tpu.parallel import sharded

            self.state = sharded.init_plan_state(
                self._halo_plan, self.config, self.mesh, seed=seed)
        elif self._node_like:
            self.state = self._node_kernel.init_state()
        elif self.mesh is not None:
            from flow_updating_tpu.parallel import auto

            self.state, self._topo_arrays = auto.init_sharded_state(
                self._padded_topology, self.config, self._n_real,
                self.mesh, seed=seed,
            )
        else:
            self.state = init_state(self.topology, self.config, seed=seed)
        return self

    # ---- observability ---------------------------------------------------
    def add_watcher(
        self,
        run_until: float = 1000.0,
        time_interval: float = 10.0,
        callback: Callable | None = None,
    ) -> Engine:
        """The reference's watcher actor (``collectall.py:139-148``): sample
        global state every ``time_interval`` simulated seconds, and at
        ``run_until`` stop all peers ("kill_all").

        Registering a watcher whose deadline lies in the future revives a
        previously killed run: a checkpoint taken after a watcher fired
        restores ``killed`` (faithful dead-time semantics within the saved
        run), but a *new* watcher with a later deadline is an explicit
        request to keep simulating — without this, ``--resume --until T``
        past an old deadline would silently freeze every peer.
        """
        if self.host_actors:
            raise NotImplementedError(
                "host_actors mode runs watchers as ordinary s4u actors, "
                "exactly like the reference: "
                "s4u.Actor.create('watcher', host, fn, deadline, every) "
                "(see examples/host_actors.py::watcher)")
        if self._killed and float(run_until) > self._clock:
            logger.info(
                "[%0.1f] watcher: reviving peers (new deadline %.1f)",
                self._clock, float(run_until),
            )
            self._killed = False
            # prune expired watchers, or the first run_until event would
            # immediately re-kill the revived peers at their old deadline
            self._watchers = [
                w for w in self._watchers if w["until"] > self._clock
            ]
        self._watchers.append(
            {"until": float(run_until), "every": float(time_interval),
             "callback": callback}
        )
        return self

    def global_values(self) -> dict:
        """The reference's ``global_values`` mirror: per-host value and
        last_avg keyed by host name (``collectall.py:47-63,131``)."""
        if self.host_actors:
            raise NotImplementedError(
                "host_actors mode: state lives inside the user's Python "
                "actors — keep your own global_values mirror like the "
                "reference does (examples/host_actors.py)")
        if self.state is None:
            return {}
        names = self.topology.names or tuple(
            str(i) for i in range(self.topology.num_nodes)
        )
        if self._halo_mode:
            from flow_updating_tpu.parallel import sharded

            value = self.topology.values
            last_avg = sharded.gather_node_array(
                self.state.last_avg, self._halo_plan)
        elif self._node_like:
            value = self.topology.values
            last_avg = self._node_kernel.last_avg(self.state)
        else:
            n = self._n_real or self.topology.num_nodes
            value = np.asarray(self.state.value)[:n]
            last_avg = np.asarray(self.state.last_avg)[:n]
        return {
            "value": dict(zip(names, value.tolist())),
            "last_avg": dict(zip(names, last_avg.tolist())),
        }

    def estimates(self) -> np.ndarray:
        if self.host_actors:
            raise NotImplementedError(
                "host_actors mode: state lives inside the user's Python "
                "actors (the reference keeps its own global_values mirror, "
                "collectall.py:131) — expose it from the actor, as "
                "examples/host_actors.py does")
        if self.state is None:
            raise RuntimeError("engine not built")
        if self._halo_mode:
            from flow_updating_tpu.parallel import sharded

            return sharded.gather_estimates(self.state, self._halo_plan)
        if self._node_like:
            return self._node_kernel.estimates(self.state)
        est = np.asarray(node_estimates(self.state, self._topo_arrays))
        return est[: self._n_real] if self._n_real is not None else est

    def convergence_report(self) -> dict:
        """Convergence + invariant metrics for the current state."""
        est = self.estimates()
        err = est - self.topology.true_mean
        report = {
            # halo-mode state carries one lockstep clock per shard
            "t": int(np.asarray(self.state.t).ravel()[0]),
            "rmse": float(np.sqrt(np.mean(err * err))),
            "max_abs_err": float(np.max(np.abs(err))),
            "mass_residual": float(est.sum() - self.topology.values.sum()),
        }
        if self.config.kernel == "edge" and not self._halo_mode:
            flow = np.asarray(self.state.flow)[: self.topology.num_edges]
            report["antisymmetry_residual"] = float(
                np.max(np.abs(flow + flow[self.topology.rev]))
            )
        elif self._halo_mode:
            # edge flows live in per-shard slots; pair them through the
            # plan's reverse routing (tshard/tlocal) to check the
            # invariant across shard boundaries too
            pl = self._halo_plan
            flow = np.asarray(self.state.flow)
            ts = np.asarray(pl.arrays.tshard)
            tl = np.asarray(pl.arrays.tlocal)
            real = tl < pl.Eb
            rev_flow = flow[ts[real], tl[real]]
            report["antisymmetry_residual"] = float(
                np.max(np.abs(flow[real] + rev_flow))
            )
        return report

    # ---- fault injection (SURVEY.md §5) ---------------------------------
    def _require_edge_kernel(self, what: str) -> None:
        if self.config.kernel != "edge":
            raise ValueError(
                f"{what} needs per-edge state; the node-collapsed kernel is "
                "exactly the fault-free fast path — use kernel='edge'"
            )
        if self._halo_mode:
            # the (S, Nb)/(S, Eb) block layout does not accept global
            # node/edge ids; silently scattering into the shard axis
            # would corrupt state
            raise NotImplementedError(
                f"{what} is not supported on the halo kernel's blocked "
                "layout yet — use the GSPMD path (multichip='auto') for "
                "fault-injection runs"
            )

    def _node_ids(self, nodes) -> np.ndarray:
        name_to_id = None
        ids = []
        for n in nodes:
            if isinstance(n, str):
                if name_to_id is None:
                    name_to_id = self.topology.name_to_id()
                ids.append(name_to_id[n])
            else:
                ids.append(int(n))
        return np.asarray(ids, dtype=np.int32)

    def kill_nodes(self, nodes) -> Engine:
        """Crash-stop the given nodes (ids or host names): they stop firing,
        sending and processing.  Delivered-but-undrained messages stay queued
        and are processed on revival — the protocol's idempotent state
        exchange makes the whole sequence self-healing (the fault model the
        Flow-Updating paper targets; the reference only exercises it through
        message loss, SURVEY.md §5).  The mask edit is the shared churn
        primitive (service/membership.py)."""
        from flow_updating_tpu.service import membership

        self._require_edge_kernel("kill_nodes")
        if self.state is None:
            raise RuntimeError("engine not built")
        self.state = membership.set_alive(
            self.state, self._node_ids(nodes), False)
        return self

    def revive_nodes(self, nodes) -> Engine:
        from flow_updating_tpu.service import membership

        self._require_edge_kernel("revive_nodes")
        if self.state is None:
            raise RuntimeError("engine not built")
        self.state = membership.set_alive(
            self.state, self._node_ids(nodes), True)
        return self

    def _edge_ids(self, links) -> np.ndarray:
        """Directed edge indices for (u, v) node pairs, both directions."""
        topo = self.topology
        topo._require_edges("fail_links/heal_links (edge lookup)")
        keys = topo.src.astype(np.int64) * topo.num_nodes + topo.dst
        ids = []
        for u, v in links:
            u, v = (int(x) for x in self._node_ids([u, v]))
            for a, b in ((u, v), (v, u)):
                key = a * topo.num_nodes + b  # Python ints: no int32 wrap
                e = int(np.searchsorted(keys, key))
                if e >= len(keys) or int(keys[e]) != key:
                    raise ValueError(f"no edge {a}->{b} in topology")
                ids.append(e)
        return np.asarray(ids, dtype=np.int64)

    def fail_links(self, links) -> Engine:
        """Fail the given undirected links (pairs of node ids or names):
        every message put on them is lost, in both directions, until
        :meth:`restore_links`.  Senders' ledgers still update — the exact
        semantics of a lost ``put_async``."""
        self._require_edge_kernel("fail_links")
        if self.state is None:
            raise RuntimeError("engine not built")
        ids = self._edge_ids(links)
        self.state = self.state.replace(
            edge_ok=self.state.edge_ok.at[ids].set(False)
        )
        return self

    def restore_links(self, links) -> Engine:
        self._require_edge_kernel("restore_links")
        if self.state is None:
            raise RuntimeError("engine not built")
        ids = self._edge_ids(links)
        self.state = self.state.replace(
            edge_ok=self.state.edge_ok.at[ids].set(True)
        )
        return self

    # ---- checkpoint / resume --------------------------------------------
    def save_checkpoint(self, path: str) -> Engine:
        """Write the full run state (one pytree) + config + topology
        fingerprint to ``path``.  The reference has no checkpointing
        (SURVEY.md §5); here it is a by-product of the array design."""
        from flow_updating_tpu.utils.checkpoint import save_checkpoint

        if self.state is None:
            raise RuntimeError("engine not built — nothing to checkpoint")
        if self._halo_mode:
            # gather the blocked layout back to the CANONICAL
            # single-device state: the checkpoint is then a standard one,
            # restorable on ANY execution mode (single-device, GSPMD, or
            # another halo mesh)
            from flow_updating_tpu.parallel import sharded

            canon = sharded.gather_full_state(
                self.state, self._halo_plan, self.topology)
            save_checkpoint(
                path, canon, self.config, topo=self.topology,
                extra={"clock": self._clock, "killed": self._killed},
            )
            return self
        if self._custom_actor is not None:
            from flow_updating_tpu.utils.checkpoint import (
                save_actor_checkpoint,
            )

            save_actor_checkpoint(
                path, self.state, self._custom_actor.name,
                topo=self.topology,
                extra={"clock": self._clock, "killed": self._killed},
            )
            return self
        if self._pod_mode:
            # flatten pod sections to the canonical structured-NodeKernel
            # layout (same convention as the halo gather above): the
            # checkpoint is then a standard node-kernel one, restorable
            # single-device, GSPMD, or on another pod mesh
            state = self._node_kernel.to_canonical(self.state)
        else:
            state = self.state
        save_checkpoint(
            path, state, self.config, topo=self.topology,
            extra={"clock": self._clock, "killed": self._killed},
        )
        return self

    def restore_checkpoint(self, path: str) -> Engine:
        """Resume from a checkpoint taken on the *same* topology (verified
        by content fingerprint).  Restores state, config and clock;
        ``build()`` is not required first.  Built-in kernels restore
        without allocating fresh state; a VectorActor restore DOES run
        the actor's ``init`` once — the fresh carry is the structural
        template the archive is validated against."""
        from flow_updating_tpu.utils.checkpoint import load_checkpoint

        if self._halo_mode:
            from flow_updating_tpu.parallel import sharded

            self._resolve_topology()
            state, cfg, extra = load_checkpoint(path, topo=self.topology)
            self.config = cfg
            self._prepare_arrays()
            self.state = sharded.scatter_full_state(
                state, self._halo_plan, self.topology, cfg, self.mesh)
            self._clock = float(extra.get("clock", float(state.t)))
            self._killed = bool(extra.get("killed", False))
            return self
        if self._custom_actor is not None:
            from flow_updating_tpu.utils.checkpoint import (
                load_actor_checkpoint,
            )

            self._resolve_topology()
            self._prepare_arrays()
            template = self._node_kernel.init_state()
            self.state, extra = load_actor_checkpoint(
                path, template, self._custom_actor.name,
                topo=self.topology)
            self._clock = float(extra.get("clock", 0.0))
            self._killed = bool(extra.get("killed", False))
            return self
        self._resolve_topology()
        state, cfg, extra = load_checkpoint(path, topo=self.topology)
        self.config = cfg
        self._prepare_arrays()
        if self.config.kernel == "edge" and self.mesh is not None:
            from flow_updating_tpu.parallel import auto

            self._topo_arrays = self._padded_topology.device_arrays(
                coloring=cfg.needs_coloring
            )
            import jax

            self._topo_arrays = jax.device_put(
                self._topo_arrays,
                auto.topo_sharding(self.mesh, self._topo_arrays),
            )
        # compare the node-axis SIZE, not shape[0]: the sharded fused
        # kernel's state is (S, M/S) while the single-device kernel's is
        # (M,) — both carry padded_size node slots
        expect = (self._node_kernel.padded_size if cfg.kernel == "node"
                  else (self._padded_topology.num_nodes
                        if self.mesh is not None else self.topology.num_nodes))
        got = state.S.size if cfg.kernel == "node" else state.value.shape[0]
        if got != expect:
            raise ValueError(
                f"checkpoint state has node axis {got} but this engine's "
                f"layout expects {expect} — restore with the same "
                "mesh/padding it was saved under"
            )
        if self._pod_mode and cfg.kernel == "node":
            # archives are canonical (flat structured-NodeKernel layout,
            # see save_checkpoint); scatter sections onto the pod mesh
            self.state = self._node_kernel.from_canonical(state)
            self._clock = float(extra.get("clock", float(state.t)))
            self._killed = bool(extra.get("killed", False))
            return self
        if cfg.kernel == "node":
            # layout check runs mesh or not: a sharded (S, M/S) state is
            # NOT interchangeable with the single-device (M,) layout even
            # when the total node-slot count matches
            template = self._node_kernel.init_state()
            if state.S.shape != template.S.shape:
                raise ValueError(
                    f"checkpoint node state has shape {state.S.shape} "
                    f"but this engine's kernel uses {template.S.shape} — "
                    "the sharded fused kernel's interleaved layout is not "
                    "interchangeable with the single-device layout; "
                    "restore under the configuration it was saved with"
                )
        if self.mesh is not None:
            if cfg.kernel == "node":
                # the kernel's init_state carries the placement; reuse it
                import jax

                state = jax.device_put(
                    state, jax.tree.map(lambda x: x.sharding, template)
                )
            else:
                from flow_updating_tpu.parallel import auto

                state = auto.shard_state(state, self.mesh)
        self.state = state
        self._clock = float(extra.get("clock", float(state.t)))
        self._killed = bool(extra.get("killed", False))
        return self

    # ---- execution -------------------------------------------------------
    def _advance(self, n: int) -> None:
        """Dispatch ``n`` compiled rounds to the configured kernel.

        With an event log attached, each dispatch leaves an ``advance``
        record (simulated start time + round count + host-side dispatch
        wall time; execution is asynchronous, so ``wall_s`` measures
        dispatch — the first call of a scan length also includes its
        compile)."""
        import time as _time

        t0 = _time.perf_counter() if self.event_log is not None else 0.0
        self._advance_inner(n)
        if self.event_log is not None:
            self.event_log.emit(
                "advance", t=self._clock, rounds=n,
                wall_s=round(_time.perf_counter() - t0, 6))

    def _advance_inner(self, n: int) -> None:
        if self._halo_mode:
            from flow_updating_tpu.parallel import sharded

            self.state = sharded.run_rounds_sharded(
                self.state, self._halo_plan, self.config, self.mesh, n,
                arrays=self._halo_arrays, halo=self._halo_wire)
        elif self._node_like:
            self.state = self._node_kernel.run(self.state, n)
        else:
            self.state = run_rounds(
                self.state, self._topo_arrays, self.config, n
            )

    def run_rounds(self, n: int) -> Engine:
        if self.state is None:
            self.build()
        if not self._killed and n > 0:
            self._advance(n)
        self._clock += n * TICK_INTERVAL
        return self

    def run_telemetry(self, n: int, spec=None):
        """Run ``n`` rounds as ONE compiled scan that accumulates the
        ``spec``-selected per-round metric series on device (zero
        ``debug.callback``s; one bulk host transfer at the end).  Returns
        a :class:`~flow_updating_tpu.obs.telemetry.TelemetrySeries`.

        Dispatches to the kernel's telemetry runner (edge, node-collapsed,
        halo shard_map, pod-sharded stencil); a disabled spec runs the
        PLAIN kernel — bit-identical program to :meth:`run_rounds` — and
        returns an empty series, so telemetry-off costs nothing
        (scripts/telemetry_overhead.py holds this to < 5%).

        ``self.telemetry_timings`` afterwards holds the compile/execute
        wall-time split (compile via AOT lowering where the runner
        supports it; None otherwise) for the run manifest.
        """
        import time as _time

        from flow_updating_tpu.obs.telemetry import (
            TelemetrySeries,
            TelemetrySpec,
        )

        spec = TelemetrySpec.default() if spec is None else spec
        self.telemetry_timings = {}
        if self.state is None:
            self.build()
        if not spec.enabled or self._killed or n <= 0:
            self.run_rounds(n)
            return TelemetrySeries.empty()
        if self._custom_actor is not None:
            raise NotImplementedError(
                "telemetry series cover the built-in kernels; a custom "
                "VectorActor defines its own carry — sample it from the "
                "actor's scan instead")
        kind = self._kernel_kind
        spec = spec.for_kernel(kind)
        import jax
        import jax.numpy as jnp

        # a ready device scalar (not a Python float) so the AOT-compiled
        # runner sees the exact aval it was lowered with
        mean = jnp.asarray(self.topology.true_mean, self.config.jnp_dtype)

        compile_s = None
        t0 = _time.perf_counter()
        if kind == "halo":
            from flow_updating_tpu.parallel import sharded

            state, series = sharded.run_rounds_sharded_telemetry(
                self.state, self._halo_plan, self.config, self.mesh, n,
                spec, mean, arrays=self._halo_arrays, halo=self._halo_wire)
        elif kind == "pod":
            state, series = self._node_kernel.run_telemetry(
                self.state, n, spec)
        elif kind == "node":
            from flow_updating_tpu.models import sync

            if not isinstance(self._node_kernel, sync.NodeKernel):
                raise NotImplementedError(
                    f"telemetry is not wired into "
                    f"{type(self._node_kernel).__name__} yet — use the "
                    "plain NodeKernel (spmv='xla'|'pallas'|'benes'|"
                    "'structured'), the pod kernel, or the edge kernel")
            # tile-padded layouts (banded_fused) reduce over the
            # real-node prefix so the series is bit-exact vs the
            # unpadded twin; unpadded kernels trace unchanged
            nn = self.topology.num_nodes
            pad = getattr(self._node_kernel, "padded_size", nn)
            state, series, compile_s = _aot_timed(
                sync.run_rounds_node_telemetry, self.state,
                self._node_kernel.arrays,
                cfg=self.config, num_rounds=n, spec=spec, true_mean=mean,
                n_live=nn if pad != nn else None)
        else:
            from flow_updating_tpu.models.rounds import run_rounds_telemetry

            state, series, compile_s = _aot_timed(
                run_rounds_telemetry, self.state, self._topo_arrays,
                cfg=self.config, num_rounds=n, spec=spec, true_mean=mean)
        series = jax.block_until_ready(series)
        wall = _time.perf_counter() - t0
        self.telemetry_timings = {
            "compile_s": (round(compile_s, 6)
                          if compile_s is not None else None),
            "execute_s": round(wall - (compile_s or 0.0), 6),
        }
        self.state = state
        self._clock += n * TICK_INTERVAL
        return TelemetrySeries({k: np.asarray(v) for k, v in
                                series.items()})

    def run_fields(self, n: int, spec=None):
        """Run ``n`` rounds as ONE compiled scan that records the
        ``spec``-selected PER-NODE / PER-EDGE metric fields on device
        (:mod:`flow_updating_tpu.obs.fields`): same zero-callback design
        as :meth:`run_telemetry`, one bulk transfer at the end, but at
        topology resolution — the raw material for fault localization
        (``inspect --blame``) and run-to-run diffing.

        Dispatches to the kernel's fields runner (edge single-device and
        GSPMD, node-collapsed, halo shard_map, pod-sharded stencil) and
        re-assembles everything into ORIGINAL node/edge order.  A
        disabled spec runs the PLAIN kernel — program-identical to
        :meth:`run_rounds` — and returns an empty series; ``spec.stride``
        bounds memory by recording every k-th round (state evolution is
        bit-identical to the plain path at any stride)."""
        from flow_updating_tpu.obs.fields import (
            EDGE_FIELDS,
            FieldSeries,
            FieldSpec,
        )

        spec = FieldSpec.default() if spec is None else spec
        if self.state is None:
            self.build()
        if not spec.enabled or self._killed or n <= 0:
            self.run_rounds(n)
            return FieldSeries.empty()
        if self._custom_actor is not None:
            raise NotImplementedError(
                "field series cover the built-in kernels; a custom "
                "VectorActor defines its own carry — sample it from the "
                "actor's scan instead")
        kind = self._kernel_kind
        spec = spec.for_kernel(kind)
        if not spec.enabled:
            self.run_rounds(n)
            return FieldSeries.empty()
        if n % spec.stride:
            raise ValueError(
                f"round count {n} must be a multiple of the field "
                f"stride {spec.stride}")
        import jax
        import jax.numpy as jnp

        mean = jnp.asarray(self.topology.true_mean, self.config.jnp_dtype)
        node: dict = {}
        edge: dict = {}
        conv = None
        topk_idx = None
        if kind == "halo":
            from flow_updating_tpu.parallel import sharded

            state, conv_b, series = sharded.run_rounds_sharded_fields(
                self.state, self._halo_plan, self.config, self.mesh, n,
                spec, mean, arrays=self._halo_arrays, halo=self._halo_wire)
            series = jax.device_get(series)
            t = np.asarray(series.pop("t"))[0]
            active = np.asarray(series.pop("active"))[0]
            for name, v in series.items():
                if name in EDGE_FIELDS:
                    edge[name] = sharded.gather_edge_field_series(
                        v, self._halo_plan, self.topology)
                else:
                    node[name] = sharded.gather_node_field_series(
                        v, self._halo_plan)
            if spec.has("node_conv_round"):
                conv = sharded.gather_node_array(
                    np.asarray(conv_b), self._halo_plan)
        elif kind == "pod":
            state, conv_s, series = self._node_kernel.run_fields(
                self.state, n, spec)
            series = jax.device_get(series)
            t = np.asarray(series.pop("t"))[0]
            active = np.asarray(series.pop("active"))[0]
            for name, secs in series.items():
                node[name] = self._node_kernel.flatten_field_series(secs)
            if spec.has("node_conv_round"):
                conv = self._node_kernel.flatten_field_final(
                    jax.device_get(conv_s))
        elif kind == "node":
            from flow_updating_tpu.models import sync

            if not isinstance(self._node_kernel, sync.NodeKernel):
                raise NotImplementedError(
                    f"field recording is not wired into "
                    f"{type(self._node_kernel).__name__} yet — use the "
                    "plain NodeKernel (spmv='xla'|'pallas'|'benes'|"
                    "'structured'), the pod kernel, or the edge kernel")
            state, conv_p, series = self._node_kernel.run_fields(
                self.state, n, spec)
            series = jax.device_get(series)
            t = np.asarray(series.pop("t"))
            active = np.asarray(series.pop("active"))
            if "topk_idx" in series:
                topk_idx = self._node_kernel.original_node_ids(
                    np.asarray(series.pop("topk_idx")))
                node.update({k: np.asarray(v) for k, v in series.items()})
            else:
                node.update({
                    k: self._node_kernel.unpermute_series(np.asarray(v))
                    for k, v in series.items()})
            if spec.has("node_conv_round"):
                conv = self._node_kernel._unpermute(np.asarray(conv_p))
        else:
            from flow_updating_tpu.models.rounds import run_rounds_fields

            state, conv_p, series = run_rounds_fields(
                self.state, self._topo_arrays, self.config, n, spec, mean)
            series = jax.device_get(series)
            t = np.asarray(series.pop("t"))
            active = np.asarray(series.pop("active"))
            n_real = self._n_real  # GSPMD mesh padding (None = exact)
            E = self.topology.num_edges
            if "topk_idx" in series:
                # padded nodes are born dead (err masked to 0), so real
                # ids outrank them and indices are already original ids —
                # except when a real node's error is exactly 0 and
                # top_k's index tie-break surfaces a ghost slot: map
                # those to -1 (the node kernel's padding convention)
                topk_idx = np.asarray(series.pop("topk_idx"))
                if n_real is not None:
                    topk_idx = np.where(topk_idx < n_real, topk_idx, -1)
            for name, v in series.items():
                v = np.asarray(v)
                if name in EDGE_FIELDS:
                    edge[name] = v[:, :E]
                elif topk_idx is not None:
                    node[name] = v
                else:
                    node[name] = v[:, :n_real] if n_real is not None else v
            if spec.has("node_conv_round"):
                conv = np.asarray(conv_p)
                if n_real is not None:
                    conv = conv[:n_real]
        self.state = state
        self._clock += n * TICK_INTERVAL
        edges = None
        if edge:
            topo = self.topology
            edges = {"src": np.asarray(topo.src),
                     "dst": np.asarray(topo.dst),
                     "rev": np.asarray(topo.rev)}
        from flow_updating_tpu.obs.inspect import node_coordinates

        return FieldSeries(
            t=t, active=active, node=node, edge=edge, conv_round=conv,
            topk_idx=topk_idx, spec=spec, edges=edges,
            coords=node_coordinates(self.topology))

    def profile(self, n: int, *, execute: bool = True,
                trace_dir: str | None = None,
                roofline: bool = False) -> dict:
        """AOT cost attribution of the configured kernel's plain
        ``n``-round program: XLA's own ``cost_analysis()`` (flops, bytes
        accessed) and ``memory_analysis()`` (argument/output/temp/peak
        bytes) for the exact executable :meth:`run_rounds` dispatches,
        plus the compile-vs-execute wall split, device
        ``memory_stats()`` (TPU), and the profile layer's compile-cache
        hit counters.

        Profiling is a pure observer: it lowers the SAME jitted
        function with the SAME arguments the plain path calls (each
        kernel's ``round_program`` hook), never instruments the scan,
        and does not advance engine state — the timed execution runs
        from the current state and its result is discarded
        (tests/test_profile.py asserts program identity and
        state-untouched).

        ``trace_dir`` additionally captures a ``jax.profiler`` device
        timeline of the overlap schedule (halo mode) so the overlap
        ratio is measured from real timeline slices
        (obs/timeline.py); ``roofline`` attaches the perf lens'
        predicted-vs-measured record (obs/roofline.py) — both pure
        host-side observers: lens off lowers byte-identically.
        """
        from flow_updating_tpu.obs import profile as _prof

        if n <= 0:
            raise ValueError("profile needs a positive round count")
        if self.state is None:
            self.build()
        if self._custom_actor is not None:
            raise NotImplementedError(
                "cost attribution covers the built-in kernels; a custom "
                "VectorActor owns its scan — lower it with "
                "obs.profile.profile_program directly")
        kind = self._kernel_kind
        if kind == "halo":
            from flow_updating_tpu.parallel import sharded

            fn, args, nd = sharded.round_program(
                self.state, self._halo_plan, self.config, self.mesh, n,
                arrays=self._halo_arrays, halo=self._halo_wire)
        elif kind == "pod":
            fn, args, nd = self._node_kernel.round_program(self.state, n)
        elif kind == "node":
            if not hasattr(self._node_kernel, "round_program"):
                raise NotImplementedError(
                    f"cost attribution is not wired into "
                    f"{type(self._node_kernel).__name__} yet — every "
                    "built-in kernel exposes round_program (the "
                    "kernel-round-program lint rule); add the hook")
            fn, args, nd = self._node_kernel.round_program(self.state, n)
        else:
            fn, args, nd = (run_rounds,
                            (self.state, self._topo_arrays, self.config, n),
                            2)
        record = _prof.profile_program(fn, args, n_dynamic=nd,
                                       execute=execute, label=kind)
        record.update({
            "mode": kind,
            "rounds": n,
            "per_round": _prof.per_round(record, n),
            "topology": {"nodes": self.topology.num_nodes,
                         "edges": self.topology.num_edges},
            "config": {"kernel": self.config.kernel,
                       "variant": self.config.variant,
                       "fire_policy": self.config.fire_policy,
                       "spmv": self.config.spmv,
                       "delivery": self.config.delivery,
                       "dtype": self.config.dtype,
                       "multichip": (self.multichip
                                     if self.mesh is not None else None),
                       "halo": (self._halo_wire if kind == "halo"
                                else None),
                       "shards": (int(self.mesh.devices.size)
                                  if self.mesh is not None else 0)},
        })
        if kind == "halo":
            record["halo"] = self.halo_report()
            if self._halo_wire in ("overlap", "overlap_pallas"):
                # overlap-mode manifests carry the measured overlap
                # ratio (fraction of exchange time hidden behind the
                # interior pass); trace_dir upgrades it from the
                # three-schedule inference to real device-timeline
                # slices
                record["overlap"] = _prof.overlap_report(
                    self.state, self._halo_plan, self.config, self.mesh,
                    n, arrays=self._halo_arrays, execute=execute,
                    mode=self._halo_wire, trace_dir=trace_dir)
        if roofline:
            from flow_updating_tpu.obs import roofline as _roof

            mode = kind
            if kind in ("node", "pod") and self.config.spmv:
                mode = f"{kind}/{self.config.spmv}"
            shards = (int(self.mesh.devices.size)
                      if self.mesh is not None else 0)
            if shards:
                mode += f"@s{shards}"
            model = _roof.resolve_model()
            exec_s = record["timings"].get("execute_s")
            measured = (n / exec_s if isinstance(exec_s, (int, float))
                        and exec_s > 0 else None)
            record["roofline"] = _roof.reconcile(
                _roof.analyze(record, model, rounds=n, mode=mode),
                measured)
        return record

    def run_until_rmse(
        self, threshold: float, max_rounds: int = 100_000,
        chunk: int = 64,
    ) -> dict:
        """Advance until the estimate RMSE vs the true mean is at or
        below ``threshold`` (the driver contract SURVEY §7 step 3 names
        ``run(rounds | until_rmse)``; the threshold metric is
        BASELINE.json's rounds-to-RMSE).  State advances in compiled
        ``chunk``-round launches with one device→host RMSE check between
        launches, so the convergence test never enters the jitted
        program (no data-dependent control flow under jit).

        Returns ``{"rounds", "t", "rmse", "converged"}`` — ``rounds`` is
        the number executed by THIS call.  The RMSE is measured against
        the static deployment mean, so it is meaningful only while the
        node population is intact (no ``kill_nodes`` churn); a churned
        run should watch :meth:`convergence_report` directly instead.
        """
        if threshold <= 0:
            raise ValueError("threshold must be positive")
        if chunk <= 0:
            raise ValueError("chunk must be positive")
        if max_rounds < 0:
            raise ValueError("max_rounds must be >= 0")
        if self.state is None:
            self.build()

        def _rmse() -> float:
            err = self.estimates() - self.topology.true_mean
            return float(np.sqrt(np.mean(err * err)))

        done = 0
        rmse = _rmse()   # a state already at the threshold runs 0 rounds
        while rmse > threshold and done < max_rounds and not self._killed:
            take = min(int(chunk), max_rounds - done)
            self.run_rounds(take)
            done += take
            rmse = _rmse()
        return {
            "rounds": done,
            "t": int(np.asarray(self.state.t).ravel()[0]),
            "rmse": rmse,
            "converged": rmse <= threshold,
        }

    def run_streamed(
        self, n: int, observe_every: int = 10, emit=None
    ) -> Engine:
        """Run ``n`` rounds as ONE compiled computation, streaming watcher
        metrics to the host mid-run via ``jax.debug.callback`` (no host
        round-trips between sampling points, unlike :meth:`run_until`).
        ``emit(metrics_dict)`` defaults to an INFO log line."""
        if self.state is None:
            self.build()
        if emit is None:
            emit = _log_stream_sample  # stable identity -> jit cache reuse
        if self._halo_mode:
            # no fused streamed program for the halo kernel: chunk
            # between samples.  Samples follow the streamed contract of
            # the other kernels (models/rounds._observe_chunk): ABSOLUTE
            # state clock, alive-masked rmse/mass, real fired counts.
            from flow_updating_tpu.parallel import sharded

            done = 0
            while done < n and not self._killed:
                take = min(int(observe_every), n - done)
                self._advance(take)
                done += take
                est = self.estimates()
                alive = sharded.gather_node_array(
                    self.state.alive, self._halo_plan).astype(bool)
                cnt = max(int(alive.sum()), 1)
                err = np.where(alive, est - self.topology.true_mean, 0.0)
                from flow_updating_tpu.utils.metrics import observer_sample

                emit(observer_sample(
                    np.asarray(self.state.t).ravel()[0],
                    np.sqrt(np.sum(err * err) / cnt),
                    np.max(np.abs(err)),
                    est[alive].sum(),
                    sharded.gather_node_array(
                        self.state.fired, self._halo_plan).sum(),
                ))
            self._clock += n * TICK_INTERVAL
            return self
        if not self._killed and n > 0:
            if self._node_like:
                self.state = self._node_kernel.run_streamed(
                    self.state, n, observe_every, emit
                )
            else:
                self.state = run_rounds_streamed(
                    self.state, self._topo_arrays, self.config, n,
                    observe_every, self.topology.true_mean, emit,
                )
        self._clock += n * TICK_INTERVAL
        return self

    def _host_run_until(self, t_end: float) -> Engine:
        """host_actors mode: drive the s4u DES (actors were spawned at
        ``load_deployment``; any extras via ``s4u.Actor.create``)."""
        des = self._host_des()
        des.run_until(float(t_end))
        self._clock = des.clock
        return self

    def run_until(self, t_end: float) -> Engine:
        """Advance simulated time to ``t_end``, honoring watchers: compiled
        chunks of rounds between sampling points, host callbacks at each
        sample, and a hard stop of peer execution at a watcher's ``until``
        (after which the clock still advances to ``t_end``, like the
        reference's dead time between kill_all at t=1000 and engine stop at
        t=10000, ``collectall.py:145,164``)."""
        if self.host_actors:
            return self._host_run_until(t_end)
        if self.state is None:
            self.build()
        events = sorted(
            {w["until"] for w in self._watchers}
            | {
                t
                for w in self._watchers
                for t in np.arange(
                    self._clock + w["every"], min(w["until"], t_end) + 1e-9, w["every"]
                )
            }
            | {float(t_end)}
        )
        for t_ev in events:
            if t_ev > t_end:
                break
            n = int(round((t_ev - self._clock) / TICK_INTERVAL))
            if n > 0 and not self._killed:
                self._advance(n)
            self._clock = t_ev
            for w in self._watchers:
                hit_sample = (
                    t_ev <= w["until"]
                    and abs((t_ev - round(t_ev / w["every"]) * w["every"])) < 1e-9
                )
                if hit_sample:
                    if w["callback"] is not None:
                        w["callback"](self)
                    else:
                        for key, vals in self.global_values().items():
                            logger.info("[%0.1f] %s%s", self._clock, key, vals)
                if t_ev >= w["until"] and not self._killed:
                    logger.info(
                        "[%0.1f] watcher: stopping every peer.", self._clock
                    )
                    self._killed = True
                    if self.event_log is not None:
                        self.event_log.emit("kill_all", t=self._clock)
        self._clock = float(t_end)
        return self
