"""Command-line driver — the framework's flag system.

The reference has no CLI beyond argv passthrough into SimGrid's engine
(``Engine(sys.argv)``, ``flowupdating-collectall.py:152``) and hard-coded
constants (``TICK_INTERVAL``/``TICK_TIMEOUT``, ``collectall.py:23-24``) and
paths (``collectall.py:154-164``).  Here every knob is a real flag, including
the north-star ``--backend=jax_tpu`` gate (BASELINE.json) selecting the
execution backend before JAX initializes.

Subcommands:

``run``
    One aggregation run.  Topology from ``--platform``/``--deployment`` XML
    (the reference's input format) or a synthetic ``--generator``.  Mirrors
    the reference driver's shape: watcher sampling every ``--observe-every``
    simulated seconds until ``--until`` (``collectall.py:151-166``).

``generate``
    Emit a synthetic topology's summary (nodes/edges/degree stats) — a
    quick check of the benchmark-ladder configs.

``oracle``
    Run the native C++ reference-style discrete-event simulator on the same
    topology (the SimGrid-CPU-class baseline) and print its convergence
    report — for apples-to-apples comparisons from the shell.

``inspect``
    Topology-resolved observability: record per-node/per-edge metric
    fields on a live run, localize faults (``--blame``), diff two runs
    (``--diff``), render heatmaps (``--heatmap``) — obs/fields.py,
    obs/inspect.py, docs/OBSERVABILITY.md §7.

``train``
    Decentralized gossip-SGD / FedAvg on the vector-payload substrate
    (:mod:`flow_updating_tpu.workloads`): each node holds a parameter
    vector and a synthetic data shard, local gradient steps alternate
    with Flow-Updating averaging rounds, optionally with periodic exact
    global averaging (``--global-avg-every``, arXiv:2105.09080) and
    mid-training node churn (``--churn-kill``/``--churn-revive``).
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import os
import sys


def _select_backend(name: str, n_virtual_devices: int | None = None) -> None:
    """Pin the JAX backend.  Must run before any JAX backend initializes.

    ``jax_tpu``  — use the ambient TPU platform (axon/tpu plugin).
    ``cpu``      — force host CPU and deregister TPU plugin factories so
                   nothing contends for (or hangs on) a TPU tunnel;
                   ``n_virtual_devices`` requests that many virtual host
                   devices (a ``--shards N`` run needs an N-device mesh).
    ``auto``     — leave discovery alone.
    """
    if name == "auto":
        return
    if name == "cpu":
        from flow_updating_tpu.utils.backend import pin_cpu

        pin_cpu(n_virtual_devices=n_virtual_devices)
    elif name == "jax_tpu":
        # Clear a CPU pin so TPU discovery can happen; an explicit TPU-ish
        # pin (tpu / axon tunnel) is kept as-is.
        preset = os.environ.get("JAX_PLATFORMS", "")
        if preset and not any(p in preset for p in ("tpu", "axon")):
            del os.environ["JAX_PLATFORMS"]
            import jax

            jax.config.update("jax_platforms", None)
    else:
        raise SystemExit(f"unknown backend {name!r}")


def _add_common(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--backend", default="auto",
                    choices=("auto", "cpu", "jax_tpu"),
                    help="execution backend (north-star gate)")
    ap.add_argument("--platform", help="SimGrid-style platform XML")
    ap.add_argument("--deployment", help="SimGrid-style deployment XML")
    ap.add_argument("--generator", help="synthetic topology, e.g. "
                    "'erdos_renyi:10000', 'barabasi_albert:100000:4', "
                    "'fat_tree:16', 'ring:100:2', 'grid2d:32:32'")
    ap.add_argument("--seed", type=int, default=0)


def _add_kernel_flags(ap: argparse.ArgumentParser) -> None:
    """Kernel/dispatch selection shared by ``run``, ``profile`` and the
    live ``doctor`` (one flag vocabulary — a config profiled or doctored
    is a config that can run)."""
    ap.add_argument("--variant", default="collectall",
                    choices=("collectall", "pairwise"))
    ap.add_argument("--fire-policy", default=None,
                    choices=("reference", "every_round"),
                    help="'reference' = faithful async dynamics; "
                         "'every_round' = fast synchronous mode")
    ap.add_argument("--delivery", default="gather",
                    choices=("gather", "scatter", "benes", "benes_fused"),
                    help="message-delivery formulation (identical "
                         "semantics; gather avoids TPU scatters, benes "
                         "avoids TPU gathers too, benes_fused runs the "
                         "benes network as fused Pallas passes — the "
                         "fastest TPU form)")
    ap.add_argument("--spmv", default="xla",
                    choices=("xla", "pallas", "benes", "benes_fused",
                             "structured", "banded", "banded_fused"),
                    help="node-kernel neighbor-sum implementation "
                         "(benes_fused batches the permutation-network "
                         "stages into Pallas HBM passes; structured uses "
                         "the generator's closed-form stencil — regular "
                         "topologies only; banded runs the topology "
                         "compiler's RCM masked-roll plan, banded_fused "
                         "the whole round as ONE VMEM-resident Pallas "
                         "kernel over that plan)")
    ap.add_argument("--segment", default="auto",
                    choices=("auto", "segment", "ell", "benes",
                             "benes_fused"),
                    help="edge-kernel per-node reduction layout: jax.ops "
                         "segment primitives vs scatter-free degree-"
                         "bucketed ELL gather+row-reduce")
    ap.add_argument("--multichip", default="auto",
                    choices=("auto", "halo", "pod"),
                    help="distribution strategy under --shards: 'auto' "
                         "= GSPMD (XLA places collectives), 'halo' = "
                         "explicitly scheduled shard_map halo-exchange "
                         "kernel (edge kernel only), 'pod' = pod-sharded "
                         "fat-tree stencil (node kernel, "
                         "--spmv structured, fat_tree generator with "
                         "shards dividing k; one (k/2,)-element psum "
                         "per round)")
    ap.add_argument("--halo", default="ppermute",
                    choices=("ppermute", "allgather", "overlap",
                             "overlap_pallas", "auto"),
                    help="halo kernel's cut-edge exchange: 'ppermute' "
                         "point-to-point, 'allgather' broadcast, "
                         "'overlap' interior/frontier-split schedule "
                         "(wire hidden behind interior compute; "
                         "bit-exact vs ppermute), 'overlap_pallas' the "
                         "split schedule on the Pallas async-remote-"
                         "copy kernel (TPU), 'auto' ranked from the "
                         "plan's measured cut-edge bytes")
    ap.add_argument("--partition", default="bfs",
                    choices=("bfs", "contiguous"),
                    help="halo kernel's node partition order")
    ap.add_argument("--shards", type=int, default=0,
                    help="shard the node axis over N devices (GSPMD over a "
                         "jax Mesh; 0 = single device)")
    ap.add_argument("--kernel", default="edge", choices=("edge", "node"),
                    help="'edge' = general per-edge kernel; 'node' = "
                         "collapsed SpMV recurrence (fast synchronous "
                         "collect-all only, the throughput path)")
    ap.add_argument("--plan", default="off", choices=("off", "auto"),
                    help="'auto' = topology compiler: after the topology "
                         "resolves, pick the fastest correct kernel/spmv "
                         "for (topology, backend) — the structured "
                         "stencil on generator-regular graphs, the "
                         "compiled RCM-band + Benes/gather-remainder "
                         "plan on arbitrary graphs (overrides --kernel/"
                         "--spmv; never changes the requested dynamics; "
                         "see the `plan` subcommand and docs/PLANNER.md)")
    ap.add_argument("--drain", type=int, default=None,
                    help="msgs processed per node per round (0=unbounded; "
                         "reference semantics: 1)")
    ap.add_argument("--timeout", type=int, default=None,
                    help="collect-all tick timeout / pairwise staleness "
                         "rounds (reference: 50)")
    ap.add_argument("--delay-depth", type=int, default=None,
                    help="in-flight ring depth (latency-warped rounds)")
    ap.add_argument("--pending-depth", type=int, default=None,
                    help="per-edge mailbox FIFO depth (default: mode "
                         "default — 2 in reference mode, 1 in fast mode)")
    ap.add_argument("--drop-rate", type=float, default=0.0,
                    help="per-message loss probability (fault injection)")


def _build_topology(args):
    from flow_updating_tpu.topology.deployment import load_deployment
    from flow_updating_tpu.topology.generators import topology_from_spec
    from flow_updating_tpu.topology.platform import load_platform

    if args.generator:
        try:
            return topology_from_spec(args.generator, seed=args.seed)
        except ValueError as err:
            raise SystemExit(str(err)) from err
    if args.deployment:
        from flow_updating_tpu.engine import TICK_INTERVAL

        platform = load_platform(args.platform) if args.platform else None
        lat = getattr(args, "latency_scale", 0.0)
        return load_deployment(args.deployment).to_topology(
            platform=platform, tick_interval=TICK_INTERVAL, latency_scale=lat,
            msg_bytes=getattr(args, "msg_bytes", 104.0),
        )
    raise SystemExit("need --deployment (with optional --platform) "
                     "or --generator")


def _make_config(args):
    from flow_updating_tpu.models.config import RoundConfig

    fidelity = getattr(args, "fidelity", False)
    fire_policy = getattr(args, "fire_policy", None)
    kw = dict(variant=args.variant, drop_rate=args.drop_rate,
              kernel=getattr(args, "kernel", "edge"),
              delivery=getattr(args, "delivery", "gather"),
              spmv=getattr(args, "spmv", "xla"),
              segment_impl=getattr(args, "segment", "auto"))
    iters = getattr(args, "contention_iters", None)
    if fidelity:
        # the RoundConfig.fidelity preset is the single source of the
        # preset values; only knobs the user explicitly set are passed,
        # so they win over the preset's setdefaults
        if fire_policy not in (None, "reference"):
            raise SystemExit(
                "--fidelity runs the faithful dynamics; it cannot "
                "combine with --fire-policy every_round")
        maker = RoundConfig.fidelity
        if iters is not None:
            kw["contention_iters"] = iters
        if getattr(args, "contention_backlog", False):
            kw["contention_backlog"] = True
    else:
        maker = (RoundConfig.reference
                 if (fire_policy or "reference") == "reference"
                 else RoundConfig.fast)
        kw["contention"] = getattr(args, "contention", False)
        kw["contention_iters"] = iters if iters is not None else 0
        kw["contention_backlog"] = getattr(args, "contention_backlog",
                                           False)
    if args.drain is not None:
        kw["drain"] = args.drain
    if args.timeout is not None:
        kw["timeout"] = args.timeout
    if args.delay_depth is not None:
        kw["delay_depth"] = args.delay_depth
    if getattr(args, "pending_depth", None) is not None:
        kw["pending_depth"] = args.pending_depth
    try:
        return maker(**kw)
    except ValueError as err:
        raise SystemExit(f"invalid flag combination: {err}") from err


def _resolve_latency_scale(args) -> None:
    """Settle the run subcommand's ``--latency-scale`` (parser default
    ``None`` = not given).  Under ``--fidelity`` with a ``--platform``
    (whose XML carries per-link latencies) the preset defaults to 1.0 —
    the preset that exists to encode the measured-best fidelity config
    must default its own prerequisite (VERDICT r5 weak #5); everywhere
    else the historical default 0.0 (unit delay) stands."""
    if getattr(args, "latency_scale", None) is None:
        args.latency_scale = (
            1.0 if getattr(args, "fidelity", False) and args.platform
            else 0.0)


def cmd_run(args) -> int:
    import time as _time

    _select_backend(args.backend,
                    n_virtual_devices=getattr(args, "shards", None) or None)
    _resolve_latency_scale(args)

    from flow_updating_tpu.engine import Engine

    cfg = _make_config(args)
    telemetry_spec = None
    if args.telemetry is not None:
        from flow_updating_tpu.obs.telemetry import TelemetrySpec

        try:
            telemetry_spec = TelemetrySpec.parse(args.telemetry)
        except ValueError as err:
            raise SystemExit(f"--telemetry: {err}") from err
        if not telemetry_spec.enabled:
            # '--telemetry off' means exactly that: the plain run paths
            # (watcher, --stream, --until-rmse) all stay available
            telemetry_spec = None
    if telemetry_spec is not None:
        if args.stream or args.until_rmse is not None:
            raise SystemExit(
                "--telemetry accumulates the series inside one fixed-"
                "length compiled scan; it cannot combine with --stream "
                "or --until-rmse")
        if args.event_log:
            # watch-record emission needs these four; fail before the
            # run, not after the compute
            need = [m for m in ("rmse", "max_abs_err", "mass",
                                "fired_total")
                    if not telemetry_spec.has(m)]
            if need:
                raise SystemExit(
                    f"--telemetry with --event-log needs metric(s) "
                    f"{','.join(need)} for the watch records — add them "
                    "to the list or use '--telemetry default'")
    if getattr(args, "multichip", "auto") in ("halo", "pod") \
            and not args.shards:
        raise SystemExit(
            f"--multichip {args.multichip} needs --shards N (it is a "
            "multi-chip distribution strategy)")
    mesh = None
    if args.shards:
        from flow_updating_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.shards)

    from flow_updating_tpu.utils.eventlog import EventLog

    event_log = EventLog(args.event_log) if args.event_log else None
    engine = Engine(config=cfg, mesh=mesh,
                    multichip=getattr(args, "multichip", "auto"),
                    halo=getattr(args, "halo", "ppermute"),
                    partition=getattr(args, "partition", "bfs"),
                    event_log=event_log,
                    plan=getattr(args, "plan", "off"))
    engine.set_topology(_build_topology(args))
    t_build0 = _time.perf_counter()
    if args.resume:
        # restore allocates no fresh state; the checkpoint's config governs
        # the run (it is part of the run's identity — e.g. delay_depth
        # shapes the ring buffer).
        try:
            engine.restore_checkpoint(args.resume)
        except ValueError as err:
            # covers both bad checkpoints (format/fingerprint/dtype) and
            # config-validity errors raised while rebuilding kernels
            raise SystemExit(f"cannot resume from {args.resume}: {err}") from err
        if engine.config != cfg:
            logging.getLogger("flow_updating_tpu.cli").warning(
                "--resume: checkpoint config %s overrides CLI flags %s",
                engine.config, cfg,
            )
    else:
        try:
            engine.build(latency_scale=args.latency_scale, seed=args.seed)
        except (ValueError, NotImplementedError) as err:
            # NotImplementedError covers explicit unsupported-mode guards
            # (e.g. halo + contention) — a clean exit, not a traceback
            raise SystemExit(f"invalid flag combination: {err}") from err
    build_s = _time.perf_counter() - t_build0

    from flow_updating_tpu.utils.trace import trace

    if event_log:
        event_log.emit(
            "run_start", nodes=engine.topology.num_nodes,
            edges=engine.topology.num_edges, variant=engine.config.variant,
            fire_policy=engine.config.fire_policy,
        )

    import jax

    until_rmse_result = None
    telemetry_series = None
    t_run0 = _time.perf_counter()
    # --trace-dir and --profile are the same capture (utils/trace.py);
    # --trace-dir wins when both are given
    with trace(getattr(args, "trace_dir", None) or args.profile):
        if telemetry_spec is not None:
            # device-resident series: one compiled scan, bulk readback
            every = max(1, int(args.observe_every))
            n = (args.rounds if args.rounds is not None
                 else max(0, int(round(args.until - engine.clock))))
            try:
                telemetry_series = engine.run_telemetry(n, telemetry_spec)
            except (ValueError, NotImplementedError) as err:
                raise SystemExit(f"--telemetry: {err}") from err
            if event_log and telemetry_series:
                # the one obs emit path — same record shape as the
                # streamed observers (contract-tested)
                for rec in telemetry_series.watch_records(every):
                    event_log.emit("watch", **rec)
        elif args.until_rmse is not None:
            until_rmse_result = engine.run_until_rmse(
                args.until_rmse, max_rounds=args.max_rounds)
            if event_log:
                event_log.emit("until_rmse", **until_rmse_result)
        elif args.stream:
            emit = None
            if event_log:
                emit = lambda m: event_log.emit("watch", **m)
            # --until is absolute simulated time (matches run_until even
            # after --resume); --rounds is a relative count.
            n = (args.rounds if args.rounds is not None
                 else max(0, int(round(args.until - engine.clock))))
            every = max(1, int(args.observe_every))
            full = n - n % every
            if full:
                engine.run_streamed(full, observe_every=every, emit=emit)
            if n - full:  # remainder rounds, unobserved — nothing truncated
                engine.run_rounds(n - full)
        elif args.rounds is not None:
            engine.run_rounds(args.rounds)
        else:
            cb = None
            if event_log:
                import numpy as np

                # halo-mode state carries one lockstep clock per shard
                cb = lambda e: event_log.emit(
                    "watch", t=int(np.asarray(e.state.t).ravel()[0]), **{
                        k: v for k, v in e.global_values().items()
                    },
                )
            engine.add_watcher(run_until=args.until,
                               time_interval=args.observe_every, callback=cb)
            engine.run_until(args.until)
        # keep execution (not just dispatch) inside the profiler trace, and
        # flush pending debug-callback effects before reporting
        if engine.state is not None:
            jax.block_until_ready(engine.state)
        jax.effects_barrier()
    run_s = _time.perf_counter() - t_run0

    report = engine.convergence_report()
    if until_rmse_result is not None:
        report["until_rmse"] = until_rmse_result
    report["true_mean"] = engine.topology.true_mean
    report["nodes"] = engine.topology.num_nodes
    report["edges"] = engine.topology.num_edges
    report["variant"] = engine.config.variant
    report["fire_policy"] = engine.config.fire_policy
    if engine.plan_report() is not None:
        report["plan"] = engine.plan_report()
    if telemetry_series is not None:
        # summary on stdout; the full series belongs in --report/--event-log
        report["telemetry"] = telemetry_series.summary()
    if args.save_checkpoint:
        engine.save_checkpoint(args.save_checkpoint)
        report["checkpoint"] = args.save_checkpoint
    if args.report:
        from flow_updating_tpu.obs.report import build_manifest, write_report

        timings = {"build_s": round(build_s, 6), "run_s": round(run_s, 6)}
        timings.update(engine.telemetry_timings or {})
        write_report(args.report, build_manifest(
            argv=getattr(args, "_argv", None), config=engine.config,
            topo=engine.topology, report=report, timings=timings,
            telemetry=telemetry_series,
        ))
        report["report_path"] = args.report
    if event_log:
        event_log.emit("run_end", **report)
        event_log.close()
    print(json.dumps(report))
    return 0


def _parse_churn(kill_spec, revive_spec, num_nodes: int, outer_steps: int):
    """``--churn-kill STEP:ID[,ID...]`` / ``--churn-revive STEP:ID[,...]``
    -> the trainer's ``{step: (verb, ids)}`` schedule.

    Validated against the run: a step past the horizon or a node id
    outside [0, N) would be a silent no-op (the trainer never reaches
    the step; JAX drops out-of-bounds scatter updates) while the report
    still records the churn as applied — reject instead."""
    churn = {}
    for verb, spec in (("kill", kill_spec), ("revive", revive_spec)):
        if not spec:
            continue
        step_s, sep, ids_s = spec.partition(":")
        try:
            if not sep:
                raise ValueError("missing ':'")
            ids = [int(i) for i in ids_s.split(",") if i]
            step = int(step_s)
            if not ids:
                raise ValueError("no node ids")
        except ValueError as err:
            raise SystemExit(
                f"--churn-{verb} {spec!r}: expected STEP:ID[,ID...] "
                f"({err})") from err
        if not 0 <= step < outer_steps:
            raise SystemExit(
                f"--churn-{verb} {spec!r}: step {step} is outside the "
                f"run (0 <= step < --outer-steps {outer_steps})")
        bad = [i for i in ids if not 0 <= i < num_nodes]
        if bad:
            raise SystemExit(
                f"--churn-{verb} {spec!r}: node id(s) {bad} outside "
                f"[0, {num_nodes}) for this topology")
        if step in churn:
            # the schedule is one action per step; silently letting the
            # later flag overwrite the earlier would run a different
            # experiment than the user asked for
            raise SystemExit(
                f"--churn-{verb} {spec!r}: step {step} already has a "
                f"--churn-{churn[step][0]} action; use distinct steps")
        churn[step] = (verb, ids)
    return churn


def cmd_train(args) -> int:
    _select_backend(args.backend)
    import jax

    if args.dtype == "float64":
        # the trainer's default precision; without x64 jax silently
        # downcasts to f32 (with a warning per array)
        jax.config.update("jax_enable_x64", True)
    import numpy as np

    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.workloads import (
        GossipSGDConfig,
        GossipSGDTrainer,
        centralized_solution,
        make_dataset,
    )

    if args.features < 1:
        raise SystemExit("--features must be >= 1 (the model parameter "
                         "dimension)")
    if args.samples_per_node < 1:
        raise SystemExit("--samples-per-node must be >= 1")
    topo = _build_topology(args)
    try:
        ds = make_dataset(
            topo.num_nodes, args.features,
            samples_per_node=args.samples_per_node, task=args.task,
            noise=args.noise, heterogeneity=args.heterogeneity,
            dirichlet_alpha=args.dirichlet_alpha, seed=args.seed,
        )
    except ValueError as err:
        raise SystemExit(f"invalid dataset flags: {err}") from err
    maker = (RoundConfig.reference if args.fire_policy == "reference"
             else RoundConfig.fast)
    try:
        gcfg = GossipSGDConfig(
            lr=args.lr, local_steps=args.local_steps,
            comm_rounds=args.comm_rounds, outer_steps=args.outer_steps,
            global_avg_every=args.global_avg_every,
        )
        rcfg = maker(variant=args.variant, dtype=args.dtype)
        trainer = GossipSGDTrainer(
            topo, ds, gcfg, round_cfg=rcfg, chunk=args.chunk,
            feature_shards=args.feature_shards,
            rounds_per_visit=args.rounds_per_visit or None)
    except ValueError as err:
        raise SystemExit(f"invalid flag combination: {err}") from err
    churn = _parse_churn(args.churn_kill, args.churn_revive,
                         topo.num_nodes, args.outer_steps)

    from flow_updating_tpu.utils.eventlog import EventLog

    event_log = EventLog(args.event_log) if args.event_log else None
    cb = None
    if event_log:
        cb = lambda k, tr: event_log.emit(
            "train_sample", step=k,
            consensus_dispersion=tr.consensus_dispersion(),
            max_mass_residual=float(np.abs(tr.mass_residual()).max()),
        )
    import time as _time

    t_run0 = _time.perf_counter()
    report = trainer.train(churn=churn,
                           sample_every=args.sample_every if cb else 0,
                           callback=cb)
    run_s = _time.perf_counter() - t_run0
    report["distance_to_centralized"] = trainer.distance_to_centralized(
        centralized_solution(ds))
    report["churn"] = {str(k): [v[0], list(map(int, v[1]))]
                       for k, v in churn.items()}
    if args.report:
        from flow_updating_tpu.obs.report import build_manifest, write_report

        write_report(args.report, build_manifest(
            argv=getattr(args, "_argv", None),
            config={"round": rcfg, "train": gcfg}, topo=topo,
            report=report, timings={"run_s": round(run_s, 6)},
        ))
        report["report_path"] = args.report
    if event_log:
        event_log.emit("train_end", **{
            k: v for k, v in report.items() if not isinstance(v, dict)})
        event_log.close()
    print(json.dumps(report))
    return 0


def _csv_list(text, cast, flag: str):
    if text is None:
        return (None,)
    try:
        vals = tuple(cast(v) for v in text.split(",") if v.strip())
    except ValueError:
        raise SystemExit(f"{flag} {text!r}: expected a comma list of "
                         f"{cast.__name__} values") from None
    if not vals:
        raise SystemExit(f"{flag} {text!r}: no values")
    return vals


def cmd_sweep(args) -> int:
    """``sweep``: batched multi-instance execution — pack a grid of
    (topology, seed, params) instances into shape buckets, one compiled
    vmapped program per bucket (flow_updating_tpu.sweep)."""
    import time as _time

    _select_backend(args.backend)
    import numpy as np

    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.obs.telemetry import TelemetrySpec
    from flow_updating_tpu.sweep import grid_instances, run_sweep
    from flow_updating_tpu.topology.generators import topology_from_spec

    topos = []
    for spec in args.generator:
        try:
            topos.append((spec, topology_from_spec(spec, seed=args.seed)))
        except ValueError as err:
            raise SystemExit(str(err)) from err

    drop_rates = _csv_list(args.drop_rates, float, "--drop-rates")
    timeouts = _csv_list(args.timeouts, int, "--timeouts")
    latency_scales = _csv_list(args.latency_scales, float,
                               "--latency-scales")
    maker = (RoundConfig.reference if args.fire_policy == "reference"
             else RoundConfig.fast)
    try:
        cfg = maker(variant=args.variant, dtype=args.dtype)
        ls_max = max((ls for ls in latency_scales if ls is not None),
                     default=0.0)
        if ls_max > 0:
            # the ring buffer must cover the worst traced-scaled delay,
            # or the params path's clamp flattens the latency sweep
            import dataclasses as _dc

            max_d = max(t.max_delay for _, t in topos)
            need = int(np.ceil(max_d * ls_max))
            cfg = _dc.replace(cfg, delay_depth=max(cfg.delay_depth, need))
    except ValueError as err:
        raise SystemExit(f"invalid flag combination: {err}") from err

    seeds = [args.seed + i for i in range(max(1, args.seeds))]
    instances = grid_instances(topos, seeds=seeds, drop_rates=drop_rates,
                               timeouts=timeouts,
                               latency_scales=latency_scales)
    spec = TelemetrySpec.default()
    if args.telemetry is not None:
        try:
            spec = TelemetrySpec.parse(args.telemetry)
        except ValueError as err:
            raise SystemExit(f"--telemetry: {err}") from err
    t0 = _time.perf_counter()
    try:
        records, summary = run_sweep(
            instances, cfg, args.rounds, spec=spec,
            rmse_threshold=args.rmse_threshold,
            max_batch=args.max_batch or None,
            include_series=args.include_series,
            profile=args.profile)
    except ValueError as err:
        raise SystemExit(f"invalid sweep configuration: {err}") from err
    wall_s = _time.perf_counter() - t0

    out = dict(summary)
    out["wall_s"] = round(wall_s, 6)
    exits = [r["convergence"]["converged_round"] for r in records
             if r["convergence"]["converged"]]
    out["median_exit_round"] = (
        int(np.median(exits)) if exits else None)
    if args.report:
        from flow_updating_tpu.obs.report import (
            build_sweep_manifest,
            write_report,
        )

        write_report(args.report, build_sweep_manifest(
            argv=getattr(args, "_argv", None), config=cfg,
            instances=records, summary=summary,
            timings={"wall_s": round(wall_s, 6)}))
        out["report_path"] = args.report
    print(json.dumps(out))
    return 0


def _parse_service_events(lines):
    """Parse a ``serve`` event script into (lineno, verb, args) tuples.

    Grammar (one event per line, ``#`` comments):

    ``run K`` | ``join V[,V...]`` | ``leave IDS`` | ``update ID V[,V...]``
    | ``add-edge U V`` | ``remove-edge U V`` | ``suspend IDS`` |
    ``resume IDS`` | ``estimates [MAX_STALENESS]`` | ``checkpoint PATH``
    """
    def _ids(tok):
        return [int(x) for x in tok.split(",")]

    def _vals(tok):
        v = [float(x) for x in tok.split(",")]
        return v[0] if len(v) == 1 else v

    out = []
    for lineno, raw in enumerate(lines, start=1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        toks = line.split()
        verb, rest = toks[0], toks[1:]
        try:
            if verb == "run":
                out.append((lineno, "run", (int(rest[0]),)))
            elif verb == "join":
                out.append((lineno, "join", (_vals(rest[0]),)))
            elif verb in ("leave", "suspend", "resume"):
                out.append((lineno, verb, (_ids(rest[0]),)))
            elif verb == "update":
                out.append((lineno, "update", (_ids(rest[0]),
                                               _vals(rest[1]))))
            elif verb in ("add-edge", "remove-edge"):
                out.append((lineno, verb, (int(rest[0]), int(rest[1]))))
            elif verb == "estimates":
                k = int(rest[0]) if rest else None
                out.append((lineno, "estimates", (k,)))
            elif verb == "checkpoint":
                out.append((lineno, "checkpoint", (rest[0],)))
            else:
                raise SystemExit(
                    f"events line {lineno}: unknown verb {verb!r} "
                    "(valid: run, join, leave, update, add-edge, "
                    "remove-edge, suspend, resume, estimates, "
                    "checkpoint)")
        except (IndexError, ValueError) as err:
            raise SystemExit(
                f"events line {lineno}: cannot parse {line!r} ({err})") from err
    return out


def cmd_serve(args) -> int:
    """``serve``: the streaming service mode — one compiled program at a
    fixed capacity, scripted (or stdin) membership events applied as
    device-side edits between scan segments, zero recompiles
    (flow_updating_tpu.service, docs/SERVICE.md)."""
    import numpy as np

    _select_backend(args.backend)

    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.service import ServiceEngine

    if args.recover:
        if not args.wal:
            raise SystemExit(
                "serve: --recover needs --wal DIR (the durability "
                "directory the crashed service was journaling into)")
        try:
            svc = ServiceEngine.recover(args.wal)
        except ValueError as err:
            raise SystemExit(f"serve: {err}") from err
        topo = None
    elif args.resume:
        try:
            svc = ServiceEngine.restore_checkpoint(args.resume)
        except ValueError as err:
            raise SystemExit(f"serve: {err}") from err
        topo = None
    else:
        topo = _build_topology(args)
        maker = (RoundConfig.reference
                 if args.fire_policy == "reference" else RoundConfig.fast)
        kw = dict(variant="collectall", dtype=args.dtype,
                  drop_rate=args.drop_rate, drain=0)
        if args.timeout is not None:
            kw["timeout"] = args.timeout
        if args.fire_policy == "reference":
            kw["pending_depth"] = 1
        try:
            cfg = maker(**kw)
            svc = ServiceEngine(
                topo, args.capacity or topo.num_nodes,
                degree_budget=args.degree_budget or None,
                edge_capacity=args.edge_capacity or None,
                config=cfg, segment_rounds=args.segment_rounds,
                seed=args.seed)
        except ValueError as err:
            raise SystemExit(f"invalid service configuration: {err}") from err
    if args.wal and not args.recover:
        try:
            svc.enable_durability(args.wal,
                                  checkpoint_every=args.checkpoint_every,
                                  retain=args.retain)
        except (ValueError, OSError) as err:
            raise SystemExit(f"serve: cannot arm durability: {err}") from err

    if args.events == "-":
        events = _parse_service_events(sys.stdin.readlines())
    elif args.events:
        try:
            with open(args.events) as f:
                events = _parse_service_events(f.readlines())
        except OSError as err:
            raise SystemExit(f"serve: cannot read events: {err}") from err
    else:
        events = []

    # --trace-dir captures the whole serving body (event script +
    # trailing rounds) as one device-timeline trace; entered manually so
    # the existing flow stays un-indented.  The fu.segment annotation
    # spans (service.engine) land inside it.
    _tracer = None
    if getattr(args, "trace_dir", None):
        import contextlib as _ctxlib

        from flow_updating_tpu.utils.trace import trace as _trace

        _tracer = _ctxlib.ExitStack()
        _tracer.enter_context(_trace(args.trace_dir))

    joined = []
    for lineno, verb, a in events:
        try:
            if verb == "run":
                svc.run(a[0])
            elif verb == "join":
                joined.append(svc.join(np.asarray(a[0])))
            elif verb == "leave":
                svc.leave(a[0])
            elif verb == "suspend":
                svc.suspend(a[0])
            elif verb == "resume":
                svc.resume(a[0])
            elif verb == "update":
                ids = a[0]
                vals = np.asarray([a[1]] * len(ids))
                if svc.feature_shape and np.ndim(a[1]) == 0:
                    raise ValueError(
                        f"scalar update value for feature shape "
                        f"{svc.feature_shape}")
                svc.update(ids, vals)
            elif verb == "add-edge":
                svc.add_edges([a])
            elif verb == "remove-edge":
                svc.remove_edges([a])
            elif verb == "estimates":
                ids, est = svc.estimates(max_staleness=a[0])
                print(json.dumps({
                    "t": svc.clock, "live": len(ids),
                    "mean_estimate": float(np.mean(est)),
                    "max_staleness": a[0]}))
            elif verb == "checkpoint":
                svc.save_checkpoint(a[0])
        except (ValueError, RuntimeError) as err:
            raise SystemExit(f"serve: events line {lineno}: {err}") from err
    if args.rounds:
        try:
            svc.run(args.rounds)
        except ValueError as err:
            raise SystemExit(f"serve: {err}") from err
    if _tracer is not None:
        _tracer.close()

    report = svc.convergence_report()
    if args.checkpoint:
        svc.save_checkpoint(args.checkpoint)
    block = svc.service_block()
    out = {
        "t": svc.clock,
        "live": svc.live_count,
        "members": svc.member_count,
        "epochs": len(svc.history),
        "events": block["events_total"],
        "compile_count": block["compile_count"],
        "rmse": report["rmse"],
        "mass_residual": report["mass_residual"],
    }
    if joined:
        out["joined"] = joined
    resil = svc.resilience_block()
    if resil is not None:
        out["durability"] = {
            "dir": resil.get("dir"),
            "wal_seq": (resil.get("wal") or {}).get("last_seq"),
            "recovered": svc._recovery is not None,
        }
    # the flight recorder (obs/metrics.py): built AFTER the resilience
    # block so doctor's metrics_consistency compares same-moment facts
    trace = svc.serving_trace_block()
    if getattr(args, "metrics", None):
        if svc.metrics is None:
            raise SystemExit(
                "serve: --metrics needs the flight recorder (it is on "
                "by default; this engine was restored from an archive "
                "written without it)")
        with open(args.metrics, "w") as f:
            f.write(svc.metrics.to_prometheus())
        out["metrics_path"] = args.metrics
    if args.report:
        from flow_updating_tpu.obs.report import (
            build_service_manifest,
            write_report,
        )

        extra = {}
        if resil is not None:
            extra["recovery"] = resil
        if trace is not None:
            extra["serving_trace"] = trace
        write_report(args.report, build_service_manifest(
            argv=getattr(args, "_argv", None), config=svc.config,
            topo=topo, service=svc.service_block(),
            series=svc.boundary_series(), report=report,
            extra=extra or None))
        out["report_path"] = args.report
    print(json.dumps(out))
    return 0


def cmd_query(args) -> int:
    """``query``: the multi-tenant query fabric — one compiled
    ``(capacity, lanes)`` engine serving a stream of cohort aggregates:
    Poisson arrivals admit into free lanes (zero recompiles), per-lane
    convergence detection retires + recycles lanes between scan
    segments (flow_updating_tpu.query, docs/QUERY.md)."""
    import time as _time

    import numpy as np

    _select_backend(args.backend)

    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.query import QueryFabric

    if args.recover:
        if not args.wal:
            raise SystemExit(
                "query: --recover needs --wal DIR (the durability "
                "directory the crashed fabric was journaling into)")
        try:
            fab = QueryFabric.recover(args.wal)
        except ValueError as err:
            raise SystemExit(f"query: {err}") from err
        topo = None
    elif args.resume:
        try:
            fab = QueryFabric.restore_checkpoint(args.resume)
        except ValueError as err:
            raise SystemExit(f"query: {err}") from err
        topo = None
    else:
        topo = _build_topology(args)
        maker = (RoundConfig.reference
                 if args.fire_policy == "reference" else RoundConfig.fast)
        kw = dict(variant="collectall", dtype=args.dtype,
                  drop_rate=args.drop_rate, drain=0)
        if args.timeout is not None:
            kw["timeout"] = args.timeout
        if args.fire_policy == "reference":
            kw["pending_depth"] = 1
        mix = None
        if getattr(args, "mixing", False):
            # a-priori spectral gap for forecast-aware admission (and
            # the manifest's mixing block) — cached, so repeat serves
            # of one topology probe once (obs/spectral.py)
            from flow_updating_tpu.obs.spectral import mixing_report

            mix = mixing_report(topo, eps=args.eps)
        try:
            cfg = maker(**kw)
            fab = QueryFabric(
                topo, lanes=args.lanes,
                capacity=args.capacity or None,
                degree_budget=args.degree_budget or None,
                edge_capacity=args.edge_capacity or None,
                config=cfg, segment_rounds=args.segment_rounds,
                seed=args.seed, conv_eps=args.eps,
                admission_slo_rounds=args.admission_slo or None,
                convergence_slo_rounds=args.convergence_slo or None,
                forecast=(False if getattr(args, "no_forecast", False)
                          else None),
                admit_policy=getattr(args, "admit_policy", "observe"),
                mixing=mix)
        except ValueError as err:
            raise SystemExit(f"invalid query configuration: {err}") from err
    if args.watchdog and fab._watchdog is None:
        fab.attach_watchdog()
    if args.wal and not args.recover:
        try:
            fab.enable_durability(args.wal,
                                  checkpoint_every=args.checkpoint_every,
                                  retain=args.retain)
        except (ValueError, OSError) as err:
            raise SystemExit(f"query: cannot arm durability: {err}") from err

    # Poisson-arrival driver: random-cohort mean queries submitted at
    # --arrival-rate per round until --queries have been offered, then
    # drain (stop early once every query retires)
    rng = np.random.default_rng(args.seed + 1)
    seg = fab.svc.segment_rounds
    t0 = _time.perf_counter()
    submitted = rounds_run = 0
    while rounds_run < args.rounds:
        arrivals = min(int(rng.poisson(args.arrival_rate * seg)),
                       args.queries - submitted)
        if arrivals:
            members = fab.svc.live_ids()
            m = max(1, int(round(len(members) * args.cohort_frac)))
        for _ in range(arrivals):
            cohort = rng.choice(members, size=m, replace=False)
            fab.submit(rng.random(m), cohort=np.sort(cohort))
            submitted += 1
        try:
            fab.run(seg)
        except ValueError as err:
            raise SystemExit(f"query: {err}") from err
        rounds_run += seg
        if args.queries and submitted >= args.queries \
                and not fab.active_lanes and not fab.queued:
            break
    wall_s = _time.perf_counter() - t0

    block = fab.query_block()
    out = {
        "t": fab.clock,
        "lanes": fab.lanes,
        "submitted": submitted,
        "completed": block["retired_total"],
        "active": block["lanes"]["active"],
        "queued": block["lanes"]["queued"],
        "compile_count": block["compile_count"],
        "admission_p95": block["admission_latency"].get("p95"),
        "wall_s": round(wall_s, 3),
    }
    fb = block.get("forecast")
    if isinstance(fb, dict) and fb.get("enabled"):
        out["at_risk"] = fb["at_risk_total"]
        out["deferred"] = fb["deferred_total"]
        if fb.get("p90_abs_log_ratio") is not None:
            out["forecast_p90_abs_log_ratio"] = fb["p90_abs_log_ratio"]
    if args.checkpoint:
        fab.save_checkpoint(args.checkpoint)
    resil = fab.resilience_block()
    if resil is not None:
        out["durability"] = {
            "dir": resil.get("dir"),
            "wal_seq": (resil.get("wal") or {}).get("last_seq"),
            "recovered": fab._recovery is not None,
            "quarantined": fab.quarantined_total,
        }
    # the flight recorder (obs/metrics.py): built AFTER the resilience
    # block so doctor's metrics_consistency compares same-moment facts
    trace = fab.serving_trace_block()
    if getattr(args, "metrics", None):
        if fab.metrics is None:
            raise SystemExit(
                "query: --metrics needs the flight recorder (it is on "
                "by default; this fabric was restored from an archive "
                "written without it)")
        with open(args.metrics, "w") as f:
            f.write(fab.metrics.to_prometheus())
        out["metrics_path"] = args.metrics
    if args.report:
        from flow_updating_tpu.obs.report import (
            build_query_manifest,
            write_report,
        )

        extra = {}
        if resil is not None:
            extra["recovery"] = resil
        if trace is not None:
            extra["serving_trace"] = trace
        write_report(args.report, build_query_manifest(
            argv=getattr(args, "_argv", None), config=fab.svc.config,
            topo=topo, query=block,
            timings={"wall_s": round(wall_s, 6)},
            extra=extra or None))
        out["report_path"] = args.report
    print(json.dumps(out))
    return 0


def cmd_chaos(args) -> int:
    """``chaos``: the infrastructure-fault conformance suite
    (flow_updating_tpu.resilience.chaos) — inject each registered fault
    into a real subprocess run, exercise the declared recovery
    machinery, doctor-assert the recovery signature and require
    ``inspect --blame`` to name the planted fault at rank 1.  With
    ``--perturb`` the recovery is disabled and the signature is
    EXPECTED to fail (the negative control).  Exit 1 on any violated
    contract."""
    from flow_updating_tpu.resilience.chaos import (
        CHAOS_REGISTRY,
        get_fault,
        run_chaos,
    )

    if args.list:
        print(json.dumps({
            name: {"summary": f.summary, "kind": f.kind,
                   "kill": f.kill, "tamper": f.tamper,
                   "inject": f.inject, "watchdog": f.watchdog}
            for name, f in CHAOS_REGISTRY.items()}))
        return 0
    names = list(args.names) or sorted(CHAOS_REGISTRY)
    for n in names:
        try:
            get_fault(n)
        except ValueError as err:
            raise SystemExit(f"chaos: {err}") from err
    _select_backend(args.backend)
    results, bad = [], []
    for n in names:
        try:
            out = run_chaos(
                n, nodes=args.nodes, lanes=args.lanes,
                segment_rounds=args.segment_rounds, n_ops=args.ops,
                seed=args.seed, outdir=args.outdir,
                perturb=args.perturb)
        except (ValueError, RuntimeError) as err:
            raise SystemExit(f"chaos: {n}: {err}") from err
        if args.perturb:
            # the recovery-disabled control MUST fail its signature
            ok = out["exit_code"] != 0
        else:
            ok = out["exit_code"] == 0 and out["blame_top"] == n
        if not ok:
            bad.append(n)
        results.append({k: out[k] for k in
                        ("fault", "perturb", "overall", "blame_top",
                         "manifest_path")})
    print(json.dumps({
        "faults": names,
        "perturb": bool(args.perturb),
        "violations": bad,
        "results": results,
    }))
    return 1 if bad else 0


def cmd_generate(args) -> int:
    import numpy as np

    topo = _build_topology(args)
    deg = topo.out_deg
    print(json.dumps({
        "nodes": topo.num_nodes,
        "directed_edges": topo.num_edges,
        "degree_min": int(deg.min()),
        "degree_mean": round(float(deg.mean()), 3),
        "degree_max": int(deg.max()),
        "max_delay": topo.max_delay,
        "true_mean": round(topo.true_mean, 6),
        "values_sum": round(float(np.sum(topo.values)), 6),
    }))
    return 0


def cmd_oracle(args) -> int:
    import numpy as np

    from flow_updating_tpu import native

    if not native.available():
        raise SystemExit("native runtime unavailable (g++ missing?)")
    topo = _build_topology(args)
    timeout = args.timeout if args.timeout is not None else 50
    network = "unit-delay"
    if getattr(args, "lmm", False):
        if not topo.has_link_model:
            raise SystemExit("--lmm needs a platform topology with a link "
                             "model (--platform + --latency-scale > 0)")
        _rmse, est, last_avg, events = native.des_run_contend(
            topo, variant=args.variant, timeout=timeout, ticks=args.ticks,
            clamp_d=0, lmm=True)
        network = "dynamic max-min LMM"
    else:
        est, last_avg, events = native.des_run(
            topo, variant=args.variant, timeout=timeout, ticks=args.ticks,
        )
    err = est - topo.true_mean
    print(json.dumps({
        "ticks": args.ticks,
        "events": events,
        "network": network,
        "rmse": float(np.sqrt(np.mean(err * err))),
        "max_abs_err": float(np.max(np.abs(err))),
        "mass_residual": float(est.sum() - topo.values.sum()),
        "true_mean": topo.true_mean,
    }))
    return 0


def cmd_obs_export_trace(args) -> int:
    """``obs export-trace``: EventLog JSONL -> Chrome trace-event /
    Perfetto JSON (open in chrome://tracing or ui.perfetto.dev).
    Serving manifests (serve/query/chaos runs carrying a
    ``serving_trace`` block) render as lane tracks with query spans
    plus metric counter tracks instead."""
    from flow_updating_tpu.obs.trace import (
        eventlog_to_chrome_trace,
        read_eventlog,
        serving_manifest_to_chrome_trace,
    )

    if not os.path.exists(args.eventlog):
        raise SystemExit(f"no such event log: {args.eventlog}")
    # a run/sweep/profile/field MANIFEST is a single JSON document, not a
    # JSONL event log — the most common mix-up; name the fix instead of
    # reporting zero records (or worse, tracing a half-parsed file)
    try:
        with open(args.eventlog) as f:
            doc = json.load(f)
    except (ValueError, OSError):
        doc = None
    if isinstance(doc, dict) and "schema" in doc:
        # a one-record JSONL event log also parses as a single JSON
        # object; only the schema key marks a manifest
        if isinstance(doc.get("serving_trace"), dict) \
                or isinstance(doc.get("query"), dict):
            # a serving manifest with a flight-recorder block: the lane
            # timeline IS the trace — render it
            trace_doc = serving_manifest_to_chrome_trace(doc)
            out = args.output or (args.eventlog + ".trace.json")
            if out == "-":
                json.dump(trace_doc, sys.stdout)
                sys.stdout.write("\n")
            else:
                with open(out, "w") as f:
                    json.dump(trace_doc, f)
                print(json.dumps({
                    "trace": out, "source": doc["schema"],
                    "trace_events": len(trace_doc["traceEvents"]),
                }))
            return 0
        raise SystemExit(
            f"{args.eventlog}: this is a {doc['schema']} manifest, not "
            "an event log — export-trace consumes the JSONL file "
            "written by `run --event-log PATH` (manifests are judged "
            "by `doctor`, field manifests by `inspect`; serve/query "
            "manifests with a serving_trace block DO render here)")
    records = read_eventlog(args.eventlog)
    if not records:
        raise SystemExit(
            f"{args.eventlog}: no parseable JSONL records (is this an "
            "event log written with --event-log?)")
    doc = eventlog_to_chrome_trace(records)
    out = args.output or (args.eventlog + ".trace.json")
    if out == "-":
        json.dump(doc, sys.stdout)
        sys.stdout.write("\n")
    else:
        with open(out, "w") as f:
            json.dump(doc, f)
        print(json.dumps({
            "trace": out, "records": len(records),
            "trace_events": len(doc["traceEvents"]),
        }))
    return 0


def _engine_from_args(args):
    """Build an Engine from the shared kernel flags (``profile`` and the
    live ``doctor`` construct exactly the engine ``run`` would)."""
    from flow_updating_tpu.engine import Engine

    cfg = _make_config(args)
    if getattr(args, "multichip", "auto") in ("halo", "pod") \
            and not args.shards:
        raise SystemExit(
            f"--multichip {args.multichip} needs --shards N (it is a "
            "multi-chip distribution strategy)")
    mesh = None
    if args.shards:
        from flow_updating_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(args.shards)
    engine = Engine(config=cfg, mesh=mesh,
                    multichip=getattr(args, "multichip", "auto"),
                    halo=getattr(args, "halo", "ppermute"),
                    partition=getattr(args, "partition", "bfs"),
                    plan=getattr(args, "plan", "off"))
    engine.set_topology(_build_topology(args))
    try:
        engine.build(latency_scale=getattr(args, "latency_scale", 0.0),
                     seed=args.seed)
    except (ValueError, NotImplementedError) as err:
        raise SystemExit(f"invalid flag combination: {err}") from err
    return engine


def cmd_profile(args) -> int:
    """``profile``: AOT cost attribution of the configured kernel's
    round program — XLA's cost/memory analysis plus the
    compile-vs-execute wall split, written as a
    ``flow-updating-profile-report/v1`` manifest (obs/profile.py)."""
    _select_backend(args.backend, n_virtual_devices=args.shards or None)
    engine = _engine_from_args(args)
    try:
        prof = engine.profile(args.rounds, execute=not args.no_execute,
                              trace_dir=getattr(args, "trace_dir", None),
                              roofline=getattr(args, "roofline", False))
    except (ValueError, NotImplementedError) as err:
        raise SystemExit(f"profile: {err}") from err
    if args.report:
        from flow_updating_tpu.obs.report import (
            build_profile_manifest,
            write_report,
        )

        extra = None
        rl = prof.get("roofline")
        if isinstance(rl, dict):
            # lift the reconciled record into the manifest's perf-lens
            # block so `doctor` can judge roofline_sane/roofline_floor
            from flow_updating_tpu.obs import roofline as _roof

            extra = {"perf_lens": _roof.perf_lens_block(
                [rl], _roof.resolve_model())}
        write_report(args.report, build_profile_manifest(
            argv=getattr(args, "_argv", None), config=engine.config,
            topo=engine.topology, profile=prof, extra=extra,
        ))
        prof["report_path"] = args.report
    print(json.dumps(prof))
    return 0


def _load_inspect_manifest(path: str) -> dict:
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as err:
        raise SystemExit(f"inspect: cannot read {path}: {err}") from err
    if not isinstance(manifest, dict):
        raise SystemExit(
            f"inspect: {path} is not a manifest (expected a JSON object "
            "with a 'fields' block — write one with `inspect --report` "
            "or `run`'s field flags)")
    return manifest


def _field_series_from(manifest: dict, path: str):
    """A manifest's fields block as a FieldSeries, with mix-ups named."""
    from flow_updating_tpu.obs.fields import FieldSeries

    block = manifest.get("fields")
    if not isinstance(block, dict):
        schema = manifest.get("schema", "unknown schema")
        raise SystemExit(
            f"inspect: {path} ({schema}) has no per-node/per-edge "
            "fields block — record one with `inspect --generator ... "
            "--fields ... --report PATH` (global-telemetry manifests "
            "are judged by `doctor`)")
    return FieldSeries.from_jsonable(block)


def _load_field_series(path: str):
    return _field_series_from(_load_inspect_manifest(path), path)


def _emit_json(doc: dict, output: str | None) -> None:
    if output and output != "-":
        with open(output, "w") as f:
            json.dump(doc, f, indent=1, default=str)
            f.write("\n")
        print(json.dumps({"output": output}))
    else:
        print(json.dumps(doc, default=str))


def cmd_inspect(args) -> int:
    """``inspect``: topology-resolved observability — record per-node /
    per-edge metric fields on a live run (``--fields``, with
    ``--field-stride``/``--field-topk`` memory bounding), localize
    faults (``--blame``: straggler nodes, leaking edge pairs, divergence
    origins), diff two runs (``--diff A B``) and render ASCII heatmaps
    over the topology (``--heatmap FIELD``).  Field manifests use the
    ``flow-updating-field-report/v1`` schema (obs/report.py)."""
    from flow_updating_tpu.obs import inspect as _inspect

    if args.diff:
        a_path, b_path = args.diff
        sa, sb = _load_field_series(a_path), _load_field_series(b_path)
        try:
            out = _inspect.diff_fields(sa, sb, atol=args.diff_atol)
        except ValueError as err:
            raise SystemExit(f"inspect --diff: {err}") from err
        _emit_json({"a": a_path, "b": b_path, **out}, args.output)
        return 0

    targets = []
    if args.generator or args.deployment:
        from flow_updating_tpu.obs.fields import FieldSpec

        try:
            spec = FieldSpec.parse(
                args.fields if args.fields is not None else "default",
                stride=args.field_stride, topk=args.field_topk,
                tol=args.conv_tol)
        except ValueError as err:
            raise SystemExit(f"--fields: {err}") from err
        if not spec.enabled:
            raise SystemExit(
                "--fields off records nothing to inspect; pick a field "
                "list (or 'default'/'full')")
        _select_backend(args.backend,
                        n_virtual_devices=args.shards or None)
        import time as _time

        engine = _engine_from_args(args)
        t0 = _time.perf_counter()
        try:
            series = engine.run_fields(args.rounds, spec)
        except (ValueError, NotImplementedError) as err:
            raise SystemExit(f"inspect: {err}") from err
        run_s = _time.perf_counter() - t0
        if args.report:
            from flow_updating_tpu.obs.report import (
                build_field_manifest,
                write_report,
            )

            report = engine.convergence_report()
            report["true_mean"] = engine.topology.true_mean
            report["nodes"] = engine.topology.num_nodes
            write_report(args.report, build_field_manifest(
                argv=getattr(args, "_argv", None), config=engine.config,
                topo=engine.topology, fields=series, report=report,
                timings={"run_s": round(run_s, 6)}))
        targets.append((args.report or "<live>", series))
    sweep_targets = []
    recovery_targets = []
    for path in args.reports:
        doc = _load_inspect_manifest(path)
        if isinstance(doc.get("recovery"), dict):
            # a flow-updating-recovery-report/v1 manifest: blame ranks
            # the registered infra faults from the recovery evidence
            if not args.blame:
                raise SystemExit(
                    f"inspect: {path} is a recovery manifest — pass "
                    "--blame to rank the infra faults that explain it")
            recovery_targets.append((path, doc))
        elif (isinstance(doc.get("instances"), list)
                and not isinstance(doc.get("fields"), dict)):
            # a sweep manifest: blame ranks the worst instances and
            # cites each lane's recorded worst nodes as stragglers
            if not args.blame:
                raise SystemExit(
                    f"inspect: {path} is a sweep manifest — pass "
                    "--blame to rank its worst instances (field-level "
                    "views need a field manifest)")
            sweep_targets.append((path, doc))
        else:
            targets.append((path, _field_series_from(doc, path)))
    if not targets and not sweep_targets and not recovery_targets:
        raise SystemExit(
            "inspect: nothing to inspect — pass saved field-manifest "
            "paths, --diff A B, or a topology (--generator/"
            "--deployment) for a live field recording")

    if args.heatmap:
        if sweep_targets:
            raise SystemExit(
                "inspect: --heatmap renders per-node fields; sweep "
                "manifests carry per-instance records only (use "
                "--blame)")
        # human view: the rendered grid(s), not JSON
        for path, series in targets:
            if args.heatmap not in series:
                raise SystemExit(
                    f"inspect: field {args.heatmap!r} was not recorded "
                    f"in {path} (have: {', '.join(series.fields)})")
            vals = series[args.heatmap]
            if args.heatmap != "node_conv_round":
                try:
                    vals = vals[args.heatmap_round]
                except IndexError:
                    raise SystemExit(
                        f"inspect: --heatmap-round {args.heatmap_round} "
                        f"outside the {len(series)} recorded rows") from None
            if series.topk_idx is not None:
                raise SystemExit(
                    "inspect: heatmaps need full field rows; this run "
                    "recorded only the topk worst nodes")
            # topology coordinates are per-NODE; edge fields wrap in
            # edge-id order instead
            coords = (series.coords if args.heatmap not in series.edge
                      else None)
            print(f"# {path}: {args.heatmap}"
                  + ("" if args.heatmap == "node_conv_round" else
                     f" @ t={int(series.t[args.heatmap_round])}"))
            print(_inspect.ascii_heatmap(vals, coords,
                                         width=args.heatmap_width))
        return 0

    out = []
    for path, series in targets:
        entry = {"source": path, "fields": series.summary()}
        if args.blame:
            entry["blame"] = _inspect.blame(
                series, threshold=args.rmse_threshold)
        out.append(entry)
    for path, doc in sweep_targets:
        try:
            verdict = _inspect.blame_sweep(doc)
        except ValueError as err:
            raise SystemExit(f"inspect: {path}: {err}") from err
        out.append({"source": path, "sweep_blame": verdict})
    for path, doc in recovery_targets:
        try:
            verdict = _inspect.blame_recovery(doc)
        except ValueError as err:
            raise SystemExit(f"inspect: {path}: {err}") from err
        out.append({"source": path, "recovery_blame": verdict})
    _emit_json(out[0] if len(out) == 1 else {"inspected": out},
               args.output)
    return 0


def cmd_plan(args) -> int:
    """``plan``: run the topology compiler standalone — compile the
    graph, print the auto-selection decision with band occupancy and
    predicted per-candidate cost, optionally as a human-readable
    explanation (``--explain``) and/or a
    ``flow-updating-plan-report/v1`` manifest (``--report``)."""
    _select_backend(args.backend)
    from flow_updating_tpu.plan import select_plan
    from flow_updating_tpu.plan.rcm import offset_profile

    cfg = _make_config(args)
    topo = _build_topology(args)
    try:
        decision = select_plan(
            topo, cfg, backend=args.plan_backend or None,
            probe="aot" if args.probe else "analytic",
            max_lanes=args.max_lanes, min_fill=args.min_fill,
            remainder=args.remainder,
            autotune=True if args.autotune else None)
    except (ValueError, NotImplementedError) as err:
        raise SystemExit(f"plan: {err}") from err
    doc = decision.describe()
    doc["nodes"] = topo.num_nodes
    doc["directed_edges"] = topo.num_edges
    if args.autotune:
        # the measured-probe cache's hit/miss counters for THIS
        # invocation — a hit means zero probes ran (the cache-hit
        # contract the smoke test asserts); the same counters feed
        # plan.select.autotune_metrics' Prometheus export
        from flow_updating_tpu.plan.select import AUTOTUNE_CACHE_STATS

        doc["autotune_cache"] = dict(AUTOTUNE_CACHE_STATS)
    if getattr(args, "mixing", False):
        # a-priori convergence budget: the diffusion operator's
        # spectral gap, both provenances, persisted in the autotune
        # cache (obs/spectral.py; doctor's mixing_sane judges it)
        from flow_updating_tpu.obs.spectral import mixing_report

        doc["mixing"] = mixing_report(
            topo, plan=decision.plan
            if decision.spmv in ("banded", "banded_fused") else None)
    if args.explain:
        lines = [f"# decision: {doc['kernel']}"
                 + (f"/{doc['spmv']}" if doc.get("spmv") else "")
                 + f" on {doc['backend']}",
                 f"# {decision.reason}"]
        numeric = {c: v for c, v in doc.get("predicted_cost", {}).items()
                   if isinstance(v, (int, float))}
        for cand, cost in sorted(numeric.items(), key=lambda kv: kv[1]):
            lines.append(f"#   {cand:<16} predicted {cost:,.0f}")
        plan = decision.plan
        if plan is not None:
            offs, counts = offset_profile(topo, plan.order, top=16)
            lines.append(
                f"# band occupancy after RCM (top diagonals of "
                f"{topo.num_nodes} rows; kept lanes marked *):")
            kept = set(plan.spmv.offsets)
            for d, c in zip(offs, counts):
                mark = "*" if int(d) in kept else " "
                lines.append(
                    f"# {mark} offset {int(d):+6d}: {int(c):8d} edges "
                    f"({100.0 * c / max(topo.num_nodes, 1):5.1f}% fill)")
        mix = doc.get("mixing")
        if isinstance(mix, dict):
            pr = mix.get("predicted_rounds")
            lines.append(
                f"# mixing: gap {mix['gap']:.4g} ({mix['provenance']}) "
                f"-> ~{pr:,.0f} rounds to eps={mix['eps']:g}"
                if pr is not None and math.isfinite(pr)
                else f"# mixing: gap {mix.get('gap')!r} "
                     f"({mix.get('provenance')})")
            st, me = mix.get("structural") or {}, mix.get("measured") or {}
            if st.get("gap") is not None and me.get("gap") is not None:
                lines.append(
                    f"#   structural {st['gap']:.4g} "
                    f"(|lambda2| {st.get('lambda2', 0):.4g}, "
                    f"{st.get('iters', '?')} iters) vs measured "
                    f"{me['gap']:.4g} ({me.get('rounds', '?')} probe "
                    "rounds)")
            cache = mix.get("cache") or {}
            lines.append(
                f"#   cache {'hit' if cache.get('hit') else 'miss'}"
                f" ({cache.get('path')})")
        print("\n".join(lines), file=sys.stderr)
    if args.report:
        from flow_updating_tpu.obs.report import (
            build_plan_manifest,
            write_report,
        )

        write_report(args.report, build_plan_manifest(
            argv=getattr(args, "_argv", None), config=cfg, topo=topo,
            plan=doc))
        doc["report_path"] = args.report
    print(json.dumps(doc))
    return 0


def cmd_scenarios(args) -> int:
    """``scenarios``: the adversarial conformance suite
    (flow_updating_tpu.scenarios) — run registered scenarios (each a
    seed grid under the sweep engine plus one field-recorded blame run),
    write the ``flow-updating-scenario-report/v1`` manifest, and judge
    every scenario's declared signature in-process.  Exit 1 on any
    failing clause — the same CI contract as ``doctor`` on the saved
    manifest."""
    from flow_updating_tpu.aggregates import AGG_SCENARIOS
    from flow_updating_tpu.scenarios.registry import (
        REGISTRY,
        get_scenario,
    )

    if args.list:
        listing = {
            name: {
                "summary": scn.summary,
                "rounds": scn.rounds,
                "rmse_threshold": scn.rmse_threshold,
                "config": dict(scn.config),
                "signature": [dict(c) for c in scn.signature],
            } for name, scn in REGISTRY.items()}
        for name, scn in AGG_SCENARIOS.items():
            rec = scn.describe()
            rec.pop("name", None)
            listing[name] = rec
        print(json.dumps(listing))
        return 0
    names = list(args.names) or None
    agg_names = [n for n in (names or []) if n in AGG_SCENARIOS]
    if agg_names:
        # the per-kind aggregate fault cases (aggregates/scenarios.py)
        # run one mixed-kind fabric each, not a seed grid — dispatch
        # the whole invocation to that runner rather than splicing two
        # manifest shapes together
        if len(agg_names) != len(names):
            raise SystemExit(
                "scenarios: aggregate scenarios "
                f"({', '.join(agg_names)}) cannot mix with sweep-grid "
                "scenarios in one invocation")
        return _run_aggregate_scenarios_cli(args, names)
    if names:
        for n in names:
            try:
                get_scenario(n)
            except ValueError as err:
                raise SystemExit(f"scenarios: {err}") from err
    _select_backend(args.backend)
    from flow_updating_tpu.obs import health
    from flow_updating_tpu.scenarios.run import (
        run_scenarios,
        scenario_manifest,
    )

    seeds = [args.seed + i for i in range(max(1, args.seeds))]
    try:
        records, summary = run_scenarios(
            names, seeds=seeds, perturb=args.perturb,
            max_batch=args.max_batch or None)
    except ValueError as err:
        raise SystemExit(f"scenarios: {err}") from err
    manifest = scenario_manifest(records, summary,
                                 argv=getattr(args, "_argv", None))
    if args.report:
        from flow_updating_tpu.obs.report import write_report

        write_report(args.report, manifest)
    checks = health.check_scenario_conformance(manifest)
    out = {
        "overall": health.overall(checks),
        "scenarios": summary["scenarios"],
        "seeds": summary["seeds"],
        "sweep_compiles": summary["sweep_compiles"],
        "wall_s": summary["wall_s"],
        "checks": [c.to_jsonable() for c in checks],
    }
    if args.perturb:
        out["perturb"] = args.perturb
    if args.report:
        out["report_path"] = args.report
    print(json.dumps(out))
    return health.exit_code(checks, strict=args.strict)


def _run_aggregate_scenarios_cli(args, names) -> int:
    """The ``scenarios`` subcommand body for aggregate-kind fault cases
    (docs/AGGREGATES.md §5): run each named case's mixed-kind fabric
    under its planted adversary, judge the per-kind ``agg_*`` signature
    clauses, exit 1 on any failing clause (``--perturb
    remove_adversary`` is the negative control and fails by design)."""
    if args.perturb and args.perturb != "remove_adversary":
        raise SystemExit(
            f"scenarios: aggregate scenarios support --perturb "
            f"remove_adversary only (got {args.perturb!r})")
    _select_backend(args.backend)
    import time as _time

    from flow_updating_tpu.aggregates import (
        aggregate_scenario_manifest,
        run_aggregate_scenarios,
    )
    from flow_updating_tpu.obs import health

    t0 = _time.perf_counter()
    records, summary = run_aggregate_scenarios(
        names, perturb=args.perturb or None)
    manifest = aggregate_scenario_manifest(
        records, summary, argv=getattr(args, "_argv", None))
    if args.report:
        from flow_updating_tpu.obs.report import write_report

        write_report(args.report, manifest)
    checks = health.check_scenario_conformance(manifest)
    out = {
        "overall": health.overall(checks),
        "scenarios": summary["scenarios"],
        "kinds": summary["kinds"],
        "wall_s": round(_time.perf_counter() - t0, 3),
        "checks": [c.to_jsonable() for c in checks],
    }
    if args.perturb:
        out["perturb"] = args.perturb
    if args.report:
        out["report_path"] = args.report
    print(json.dumps(out))
    return health.exit_code(checks, strict=args.strict)


def cmd_doctor(args) -> int:
    """``doctor``: rule-based health verdicts (obs/health.py) over saved
    manifests, the recorded baselines, and/or a live telemetry run.
    Exit code 1 on any failing check (warnings too under ``--strict``) —
    the CI contract."""
    from flow_updating_tpu.obs import health

    checks = []
    for path in args.reports:
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as err:
            raise SystemExit(f"doctor: cannot read {path}: {err}") from err
        for c in health.diagnose_manifest(manifest):
            c.evidence.setdefault("source", path)
            checks.append(c)
    if args.baselines is not None:
        try:
            with open(args.baselines) as f:
                data = json.load(f)
        except (OSError, ValueError) as err:
            raise SystemExit(
                f"doctor: cannot read baselines {args.baselines}: {err}") from err
        c = health.check_baselines(data)
        c.evidence.setdefault("source", args.baselines)
        checks.append(c)
    # getattr: callers build Namespaces programmatically (tests, other
    # drivers) and may predate the --golden flag
    golden_path = getattr(args, "golden", None)
    if golden_path is not None and (args.generator or args.deployment):
        # the golden audit pins its own lowering environment (cpu, 8
        # virtual devices, x64) BEFORE jax initializes — combining it
        # with a live run would silently hijack the run's backend and
        # numerics.  Two invocations, two environments.
        raise SystemExit(
            "doctor: --golden pins the cpu+x64 audit environment and "
            "cannot share a process with a live run — run `doctor "
            "--golden` and `doctor --generator ...` separately")
    if golden_path is not None:
        # program_conformance: the golden-program ledger audit as a
        # doctor check (analysis/golden.py; same CPU pin as `audit`)
        _pin_analysis_backend()
        from flow_updating_tpu.analysis import golden

        try:
            ledger = golden.load_ledger(golden_path)
        except (OSError, ValueError) as err:
            raise SystemExit(
                f"doctor: cannot read golden ledger {golden_path}: "
                f"{err} — generate it with `audit --rebase`") from err
        c = health.check_program_conformance(golden.audit(ledger))
        c.evidence.setdefault("source", golden_path)
        checks.append(c)
    if args.generator or args.deployment:
        _select_backend(args.backend,
                        n_virtual_devices=args.shards or None)
        from flow_updating_tpu.obs.report import environment_info
        from flow_updating_tpu.obs.telemetry import TelemetrySpec

        engine = _engine_from_args(args)
        try:
            series = engine.run_telemetry(args.rounds,
                                          TelemetrySpec.full())
        except (ValueError, NotImplementedError) as err:
            raise SystemExit(f"doctor: {err}") from err
        dtype = engine.config.dtype
        checks.extend(health.diagnose_series(
            series, threshold=args.rmse_threshold, dtype=dtype))
        checks.append(health.check_environment(
            environment_info(), config={"dtype": dtype}))
        # enrich like cmd_run's printed report: check_report scales its
        # mass tolerance by true_mean x nodes — a bare report would be
        # judged at scale 1.0 and false-fail any topology with mass >> 1
        report = engine.convergence_report()
        report["true_mean"] = engine.topology.true_mean
        report["nodes"] = engine.topology.num_nodes
        checks.append(health.check_report(report, dtype=dtype))
    if not checks:
        raise SystemExit(
            "doctor: nothing to judge — pass saved report paths, "
            "--baselines, --golden, or a topology (--generator/"
            "--deployment) for a live run")
    print(json.dumps({"overall": health.overall(checks),
                      "checks": [c.to_jsonable() for c in checks]}))
    return health.exit_code(checks, strict=args.strict)


def cmd_regress(args) -> int:
    """``regress``: gate a fresh bench result / profile manifest against
    the artifact history (obs/regress.py); exit 1 beyond the recorded
    spread."""
    from flow_updating_tpu.obs import health, regress

    def _load(path):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as err:
            raise SystemExit(f"regress: cannot read {path}: {err}") from err

    fresh = _load(args.fresh)
    against = _load(args.against) if args.against else None
    checks = regress.gate(fresh, history_pattern=args.history,
                          against=against, margin_pct=args.margin)
    print(json.dumps({"overall": health.overall(checks),
                      "checks": [c.to_jsonable() for c in checks]}))
    return health.exit_code(checks)


def _pin_analysis_backend() -> None:
    """lint/audit lower the kernel matrix on the CPU backend with 8
    virtual devices and x64 enabled — the EXACT environment
    tests/conftest.py pins, because the committed ledger is the
    canonical-text table of that environment (x64 changes int widths in
    the lowering, so it is part of the ledger's identity)."""
    import jax

    from flow_updating_tpu.utils.backend import pin_cpu

    pin_cpu(n_virtual_devices=8)
    jax.config.update("jax_enable_x64", True)


def cmd_lint(args) -> int:
    """``lint``: the repo-specific AST rules (analysis/flowlint.py) plus
    the jaxpr rule engine over the standard kernel-program matrix
    (analysis/rules.py).  Exit 1 on any finding, each cited as
    ``file:line: rule: message`` / ``[program] rule at path:
    message``."""
    from flow_updating_tpu.analysis import flowlint

    findings = []
    paths = args.paths or None
    ast_findings = flowlint.lint_paths(paths)
    findings.extend(f.format() for f in ast_findings)
    if not args.ast_only and not args.paths:
        _pin_analysis_backend()
        from flow_updating_tpu.analysis import rules

        findings.extend(f.format() for f in rules.audit_kernels())
    for line in findings:
        print(line)
    if findings:
        print(f"lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("lint: clean")
    return 0


def cmd_audit(args) -> int:
    """``audit``: re-lower every golden-program cell and diff against
    the committed ledger (GOLDEN_PROGRAMS.json), naming the exact cell
    and first divergent HLO line on drift; ``--rebase`` regenerates the
    ledger after an INTENTIONAL lowering change (the diff review is the
    sign-off).  Exit 1 on drift."""
    from flow_updating_tpu.analysis import golden
    from flow_updating_tpu.obs import health
    from flow_updating_tpu.obs.report import (
        build_audit_manifest,
        write_report,
    )

    _pin_analysis_backend()
    if args.rebase:
        ledger = golden.build_ledger()
        golden.save_ledger(ledger, args.ledger)
        print(f"audit: rebased {len(ledger['cells'])} cells -> "
              f"{args.ledger}")
        if not args.report:
            return 0
        # --rebase --report: fall through and audit the fresh ledger so
        # the requested manifest exists (it records the rebased state)
    else:
        try:
            ledger = golden.load_ledger(args.ledger)
        except (OSError, ValueError) as err:
            raise SystemExit(
                f"audit: cannot read ledger {args.ledger}: {err} — "
                "generate it with `audit --rebase`") from err
    report = golden.audit(ledger)
    checks = [health.check_program_conformance(report)]
    inv_summary = None
    if not args.no_invariants:
        # the invariant prover (analysis/invariants.py): antisymmetry
        # pairing / clip symmetry / mask neutrality / observer purity
        # proved on every registered cell — trace-only, a few seconds
        from flow_updating_tpu.analysis import invariants

        inv_summary = invariants.summarize(invariants.prove_cells())
        checks.append(health.check_invariants(inv_summary))
    budget_report = None
    if args.budget:
        # the collective-byte budget verifier (analysis/budget.py):
        # compiled HLO collective bytes vs plan accounting ±5%, any
        # unbudgeted collective named — written as its own manifest
        from flow_updating_tpu.analysis import budget as budget_mod
        from flow_updating_tpu.obs.report import build_budget_manifest

        budget_report = budget_mod.verify_matrix()
        checks.append(health.check_budget(budget_report))
        write_report(args.budget, build_budget_manifest(
            argv=getattr(args, "_argv", None), budget=budget_report,
            invariants=inv_summary))
    if args.report:
        write_report(args.report, build_audit_manifest(
            argv=getattr(args, "_argv", None), audit=report,
            ledger_path=args.ledger,
            extra=({"invariants": inv_summary}
                   if inv_summary is not None else None)))
    out = {"overall": health.overall(checks),
           "check": checks[0].to_jsonable()}
    if inv_summary is not None:
        out["invariants"] = {"overall": inv_summary["overall"],
                             "counts": inv_summary["counts"]}
    if budget_report is not None:
        out["budget"] = {"overall": budget_report["overall"],
                         "failed": budget_report["failed"]}
    print(json.dumps(out))
    return health.exit_code(checks, strict=args.strict)


def _add_durability_flags(p, prog: str) -> None:
    """The crash-safety flag set shared by ``serve`` and ``query``
    (flow_updating_tpu.resilience, docs/RESILIENCE.md)."""
    p.add_argument("--wal", metavar="DIR",
                   help="durability directory: journal every event in "
                        "an fsync'd CRC-framed WAL and write automatic "
                        "ring checkpoints — a SIGKILL at any point "
                        f"recovers bit-exactly via `{prog} --wal DIR "
                        "--recover`")
    p.add_argument("--checkpoint-every", type=int, default=8,
                   metavar="K",
                   help="ring cadence: one checkpoint every K compiled "
                        "segments (with --wal)")
    p.add_argument("--retain", type=int, default=3, metavar="N",
                   help="ring retention: keep the newest N checkpoints "
                        "(corrupt newest falls back to next)")
    p.add_argument("--recover", action="store_true",
                   help="rebuild the engine from --wal DIR (newest "
                        "valid ring checkpoint + WAL replay) instead "
                        "of building fresh")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="flow_updating_tpu",
        description="TPU-native Flow-Updating distributed aggregation",
    )
    ap.add_argument("-v", "--verbose", action="store_true")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="one aggregation run")
    _add_common(run)
    _add_kernel_flags(run)
    run.add_argument("--fidelity", action="store_true",
                     help="the measured-best network-fidelity preset for "
                          "the chosen --variant (faithful dynamics + "
                          "max-min water-fill contention; backlog for "
                          "pairwise — RoundConfig.fidelity, residuals "
                          "pinned vs the dynamic LMM oracle).  Needs "
                          "--platform; --latency-scale defaults to 1.0")
    run.add_argument("--contention", action="store_true",
                     help="shared-link bandwidth contention (needs "
                          "--platform and --latency-scale > 0): concurrent "
                          "sends crossing a SHARED link split its capacity; "
                          "FATPIPE links never share")
    run.add_argument("--contention-iters", type=int, default=None,
                     help="with --contention: progressive-filling "
                          "max-min iterations per round (0 = local "
                          "bottleneck share; k>0 approximates SimGrid's "
                          "LMM water-fill — see RoundConfig)")
    run.add_argument("--contention-backlog", action="store_true",
                     help="with --contention: count still-in-flight "
                          "messages as standing link load (cross-tick "
                          "queueing; recommended for pairwise fidelity "
                          "runs — see tests/test_lmm.py)")
    run.add_argument("--latency-scale", type=float, default=None,
                     help=">0: derive per-edge delays from platform "
                          "latencies x this scale.  Default 0 (unit "
                          "delay) — except under --fidelity with a "
                          "--platform, where it defaults to 1.0 (the "
                          "platform's own latencies drive the delays)")
    run.add_argument("--msg-bytes", type=float, default=104.0,
                     help="simulated message wire size; adds the "
                          "size/bandwidth serialization term to latency-"
                          "warped delays. Default 104 is measured from "
                          "the reference PDU: FlowUpdatingMsg.size() "
                          "sums sys.getsizeof over (sender, flow, "
                          "estimate) (flowupdating-collectall.py:13-19); "
                          "the PDU's fields are fixed-size, so the "
                          "constant is exact for this protocol")
    run.add_argument("--rounds", type=int, default=None,
                     help="run exactly N rounds (no watcher)")
    run.add_argument("--until-rmse", type=float, default=None,
                     metavar="THRESH",
                     help="run until estimate RMSE <= THRESH (chunked "
                          "compiled launches; overrides --rounds/--until)")
    run.add_argument("--max-rounds", type=int, default=100_000,
                     help="round budget for --until-rmse")
    run.add_argument("--until", type=float, default=1000.0,
                     help="watcher horizon in simulated seconds "
                          "(reference: 1000)")
    run.add_argument("--observe-every", type=float, default=10.0,
                     help="watcher sampling interval (reference: 10)")
    run.add_argument("--stream", action="store_true",
                     help="one compiled run with metrics streamed mid-run "
                          "via jax.debug.callback (vs host-chunked watcher)")
    run.add_argument("--telemetry", nargs="?", const="default",
                     metavar="METRICS",
                     help="device-resident per-round metric series: run "
                          "--rounds/--until as ONE compiled scan that "
                          "accumulates metrics on device (no debug "
                          "callbacks, one bulk readback).  METRICS is "
                          "'default', 'full', or a comma list from: "
                          "rmse, max_abs_err, mass, mass_residual, "
                          "antisymmetry, sent, delivered, fired_total, "
                          "active.  Summary lands in the printed report; "
                          "full series in --report / --event-log")
    run.add_argument("--report", metavar="PATH",
                     help="write a self-describing JSON run manifest "
                          "(argv, config, topology fingerprint, backend, "
                          "compile/execute timings, convergence report, "
                          "telemetry series) to PATH")
    run.add_argument("--event-log", metavar="PATH",
                     help="append structured JSONL events (watch samples, "
                          "run start/end) to PATH")
    run.add_argument("--profile", metavar="DIR",
                     help="capture a JAX/XLA profiler trace into DIR")
    run.add_argument("--trace-dir", metavar="DIR",
                     help="alias of --profile (the bench/serve flag "
                          "name): capture the run's device timeline "
                          "into DIR; parse it with obs.timeline or "
                          "view in TensorBoard/Perfetto")
    run.add_argument("--save-checkpoint", metavar="PATH",
                     help="write the final state pytree + config to PATH")
    run.add_argument("--resume", metavar="PATH",
                     help="resume from a checkpoint (same topology required)")
    run.set_defaults(fn=cmd_run)

    tr = sub.add_parser(
        "train", help="decentralized gossip-SGD / FedAvg workload")
    _add_common(tr)
    tr.add_argument("--latency-scale", type=float, default=0.0,
                    help=">0: latency-warped comm rounds from platform "
                         "latencies (as in `run`)")
    tr.add_argument("--features", type=int, default=16,
                    help="model parameter dimension D (the vector-payload "
                         "feature axis)")
    tr.add_argument("--task", default="linear",
                    choices=("linear", "logistic"),
                    help="per-node synthetic objective")
    tr.add_argument("--samples-per-node", type=int, default=16)
    tr.add_argument("--noise", type=float, default=0.1,
                    help="label noise (linear) / logit temperature "
                         "(logistic)")
    tr.add_argument("--heterogeneity", type=float, default=0.0,
                    help="per-node feature-distribution shift (non-IID "
                         "shards; 0 = IID)")
    tr.add_argument("--dirichlet-alpha", type=float, default=None,
                    help="Dirichlet non-IID shard synthesis: node "
                         "cluster mixtures ~ Dir(alpha) over latent "
                         "feature clusters (small alpha = strongly "
                         "non-IID; omit for none)")
    tr.add_argument("--chunk", type=int, default=0,
                    help="pipelined chunked gossip: stream the D-feature"
                         " payload through edges in c-lane slices "
                         "(a divisor of D; 0 = monolithic)")
    tr.add_argument("--rounds-per-visit", type=int, default=0,
                    help="with --chunk: rounds each chunk advances per "
                         "schedule visit (0 = the config's canonical "
                         "visit length)")
    tr.add_argument("--feature-shards", type=int, default=0,
                    help="shard the payload feature axis over this many "
                         "devices (model parallelism; 0 = off)")
    tr.add_argument("--lr", type=float, default=0.2)
    tr.add_argument("--local-steps", type=int, default=1,
                    help="gradient steps per outer step")
    tr.add_argument("--comm-rounds", type=int, default=2,
                    help="Flow-Updating averaging rounds per outer step")
    tr.add_argument("--outer-steps", type=int, default=200)
    tr.add_argument("--global-avg-every", type=int, default=0,
                    help="periodic exact global averaging every H outer "
                         "steps (Gossip-PGA, arXiv:2105.09080); 0 = pure "
                         "gossip")
    tr.add_argument("--variant", default="collectall",
                    choices=("collectall", "pairwise"),
                    help="averaging protocol for the comm rounds")
    tr.add_argument("--fire-policy", default="every_round",
                    choices=("reference", "every_round"),
                    help="'reference' trains over the faithful "
                         "asynchronous message dynamics")
    tr.add_argument("--dtype", default="float64",
                    choices=("float32", "float64"))
    tr.add_argument("--churn-kill", metavar="STEP:ID[,ID...]",
                    help="kill these nodes before outer step STEP "
                         "(crash-stop churn mid-training)")
    tr.add_argument("--churn-revive", metavar="STEP:ID[,ID...]",
                    help="revive these nodes before outer step STEP")
    tr.add_argument("--sample-every", type=int, default=10,
                    help="event-log sampling cadence in outer steps")
    tr.add_argument("--event-log", metavar="PATH",
                    help="append structured JSONL train samples to PATH")
    tr.add_argument("--report", metavar="PATH",
                    help="write a self-describing JSON run manifest to "
                         "PATH (as in `run --report`)")
    tr.set_defaults(fn=cmd_train)

    sw = sub.add_parser(
        "sweep",
        help="batched multi-instance parameter sweep: pack a grid of "
             "(topology, seed, params) instances into shape buckets and "
             "run each bucket as ONE vmapped compiled program "
             "(docs/SWEEP.md)")
    sw.add_argument("--backend", default="auto",
                    choices=("auto", "cpu", "jax_tpu"),
                    help="execution backend (as in `run`)")
    sw.add_argument("--generator", action="append", required=True,
                    metavar="SPEC",
                    help="synthetic topology, e.g. 'ring:64:2' or "
                         "'erdos_renyi:1000' — repeat the flag to sweep "
                         "several topologies")
    sw.add_argument("--seed", type=int, default=0,
                    help="base seed (topology values + first instance "
                         "seed)")
    sw.add_argument("--seeds", type=int, default=1,
                    help="instance seeds per grid point: seed, seed+1, "
                         "... (independent message-loss realizations)")
    sw.add_argument("--drop-rates", metavar="CSV",
                    help="comma list of per-message loss probabilities "
                         "(traced per-instance — the whole list shares "
                         "one compile)")
    sw.add_argument("--timeouts", metavar="CSV",
                    help="comma list of timeout values (traced "
                         "per-instance)")
    sw.add_argument("--latency-scales", metavar="CSV",
                    help="comma list of traced delay multipliers "
                         "(scales each topology's static per-edge "
                         "delays; delay_depth is sized to cover the "
                         "largest)")
    sw.add_argument("--variant", default="collectall",
                    choices=("collectall", "pairwise"))
    sw.add_argument("--fire-policy", default="reference",
                    choices=("reference", "every_round"))
    sw.add_argument("--dtype", default="float32",
                    choices=("float32", "float64"))
    sw.add_argument("--rounds", type=int, default=200,
                    help="rounds per instance (every lane runs the full "
                         "count; converged lanes record their effective "
                         "early-exit round)")
    sw.add_argument("--rmse-threshold", type=float, default=1e-6,
                    help="per-instance convergence threshold for the "
                         "early-exit round")
    sw.add_argument("--max-batch", type=int, default=0,
                    help="cap lanes per bucket (0 = unbounded; same-"
                         "shape chunks still share one compile)")
    sw.add_argument("--telemetry", nargs="?", const="default",
                    metavar="METRICS",
                    help="per-instance metric selection (as in `run`; "
                         "must include rmse)")
    sw.add_argument("--include-series", action="store_true",
                    help="embed each instance's full per-round series "
                         "in the manifest records (large)")
    sw.add_argument("--profile", action="store_true",
                    help="attach per-bucket AOT cost attribution "
                         "(flops, bytes, peak memory, compile wall — "
                         "obs/profile.py) to the sweep summary/manifest")
    sw.add_argument("--report", metavar="PATH",
                    help="write the flow-updating-sweep-report/v1 "
                         "manifest (one record per instance) to PATH")
    sw.set_defaults(fn=cmd_sweep)

    sv = sub.add_parser(
        "serve",
        help="streaming service mode: one program compiled at a fixed "
             "capacity runs in scan segments while members join, leave, "
             "update values and rewire edges between segments — zero "
             "recompiles, per-feature mass conserved, doctor-checkable "
             "flow-updating-service-report/v1 manifests "
             "(docs/SERVICE.md)")
    _add_common(sv)
    sv.add_argument("--capacity", type=int, default=0,
                    help="maximum concurrent members (node slots; "
                         "default: the initial topology's node count — "
                         "no join headroom)")
    sv.add_argument("--edge-capacity", type=int, default=0,
                    help="total directed edge slots (default: initial "
                         "edges + headroom for the spare node slots)")
    sv.add_argument("--degree-budget", type=int, default=0,
                    help="per-member degree budget W (row-matrix width; "
                         "default: the initial max degree — no add-edge "
                         "headroom beyond freed slots)")
    sv.add_argument("--segment-rounds", type=int, default=32,
                    help="compiled scan length; events apply between "
                         "segments and `run` counts must be multiples")
    sv.add_argument("--rounds", type=int, default=0,
                    help="extra rounds after the event script (a whole "
                         "number of segments)")
    sv.add_argument("--events", metavar="FILE",
                    help="event script ('-' = stdin): run K / join V / "
                         "leave IDS / update IDS V / add-edge U V / "
                         "remove-edge U V / suspend IDS / resume IDS / "
                         "estimates [K] / checkpoint PATH")
    sv.add_argument("--fire-policy", default="every_round",
                    choices=("every_round", "reference"),
                    help="collect-all firing rule (the service runs "
                         "variant=collectall with unbounded drain)")
    sv.add_argument("--timeout", type=int, default=None,
                    help="collect-all tick timeout (reference firing)")
    sv.add_argument("--drop-rate", type=float, default=0.0,
                    help="per-message loss probability")
    sv.add_argument("--dtype", default="float32",
                    choices=("float32", "float64"))
    _add_durability_flags(sv, "serve")
    sv.add_argument("--resume", metavar="CKPT",
                    help="restore a service checkpoint instead of "
                         "building from a topology (bit-exact resume)")
    sv.add_argument("--checkpoint", metavar="PATH",
                    help="save a service checkpoint at exit")
    sv.add_argument("--report", metavar="PATH",
                    help="write the flow-updating-service-report/v1 "
                         "manifest (capacity accounting, per-epoch mass "
                         "history, compile count) to PATH")
    sv.add_argument("--metrics", metavar="PATH",
                    help="write the flight recorder's streaming metrics "
                         "as Prometheus text exposition to PATH at exit "
                         "(obs/metrics.py; docs/OBSERVABILITY.md §8)")
    sv.add_argument("--trace-dir", metavar="DIR",
                    help="capture the serving body (event script + "
                         "trailing rounds, with fu.segment spans at "
                         "segment boundaries) as a JAX/XLA profiler "
                         "trace into DIR (utils/trace.py; parse with "
                         "obs.timeline)")
    sv.set_defaults(fn=cmd_serve)

    qr = sub.add_parser(
        "query",
        help="multi-tenant query fabric: thousands of concurrent cohort "
             "aggregates on ONE compiled engine — Poisson arrivals "
             "admit into free payload lanes with zero recompiles, "
             "per-lane convergence detection retires + recycles lanes "
             "between scan segments, doctor-checkable "
             "flow-updating-query-report/v1 manifests (docs/QUERY.md)")
    _add_common(qr)
    qr.add_argument("--lanes", type=int, default=64,
                    help="concurrent-query capacity (the compiled "
                         "payload width D; admission beyond it queues)")
    qr.add_argument("--capacity", type=int, default=0,
                    help="maximum concurrent members (node slots; "
                         "default: the initial topology's node count)")
    qr.add_argument("--edge-capacity", type=int, default=0,
                    help="total directed edge slots (default: initial "
                         "edges + headroom)")
    qr.add_argument("--degree-budget", type=int, default=0,
                    help="per-member degree budget W (default: the "
                         "initial max degree)")
    qr.add_argument("--segment-rounds", type=int, default=32,
                    help="compiled scan length; lanes admit/retire at "
                         "segment boundaries")
    qr.add_argument("--queries", type=int, default=16,
                    help="total queries to offer (0 = just run "
                         "--rounds)")
    qr.add_argument("--arrival-rate", type=float, default=0.25,
                    help="Poisson arrival rate (queries per round)")
    qr.add_argument("--cohort-frac", type=float, default=0.25,
                    help="cohort size as a fraction of live members "
                         "(random member subsets)")
    qr.add_argument("--rounds", type=int, default=4096,
                    help="round budget (the driver stops early once "
                         "every offered query retires)")
    qr.add_argument("--eps", type=float, default=1e-6,
                    help="default per-query convergence tolerance "
                         "(relative estimate spread for retirement)")
    qr.add_argument("--admission-slo", type=int, default=0,
                    help="admission-latency SLO in rounds (doctor's "
                         "query_admission budget; default: 2 segments)")
    qr.add_argument("--convergence-slo", type=int, default=0,
                    help="convergence-latency SLO in rounds (doctor's "
                         "slo_latency p95 target; default: undeclared)")
    qr.add_argument("--no-forecast", action="store_true",
                    help="disable the per-lane convergence forecaster "
                         "(on by default with the flight recorder; the "
                         "off-fabric lowers byte-identically — "
                         "docs/OBSERVABILITY.md §10)")
    qr.add_argument("--admit-policy", default="observe",
                    choices=("observe", "strict"),
                    help="forecast-aware admission: 'observe' flags "
                         "provably-over-SLO queries at_risk but admits "
                         "them; 'strict' defers them at the door "
                         "(needs --mixing and --convergence-slo)")
    qr.add_argument("--mixing", action="store_true",
                    help="estimate the topology's spectral gap up "
                         "front (obs/spectral.py, autotune-cached) and "
                         "price admissions against it — the manifest "
                         "gains a mixing block doctor's mixing_sane "
                         "judges")
    qr.add_argument("--fire-policy", default="every_round",
                    choices=("every_round", "reference"),
                    help="collect-all firing rule")
    qr.add_argument("--timeout", type=int, default=None,
                    help="collect-all tick timeout (reference firing)")
    qr.add_argument("--drop-rate", type=float, default=0.0,
                    help="per-message loss probability")
    qr.add_argument("--dtype", default="float32",
                    choices=("float32", "float64"))
    _add_durability_flags(qr, "query")
    qr.add_argument("--watchdog", action="store_true",
                    help="arm the inline lane watchdog: NaN/divergence "
                         "lanes are quarantined mass-neutrally between "
                         "segments, admissions back off when lanes are "
                         "exhausted (flow_updating_tpu.resilience."
                         "watchdog)")
    qr.add_argument("--resume", metavar="CKPT",
                    help="restore a query-fabric checkpoint (lane "
                         "tables included) instead of building fresh")
    qr.add_argument("--checkpoint", metavar="PATH",
                    help="save a query-fabric checkpoint at exit")
    qr.add_argument("--report", metavar="PATH",
                    help="write the flow-updating-query-report/v1 "
                         "manifest (lane/compile accounting, admission "
                         "latency vs SLO, per-boundary lane-mass rows) "
                         "to PATH")
    qr.add_argument("--metrics", metavar="PATH",
                    help="write the flight recorder's streaming metrics "
                         "as Prometheus text exposition to PATH at exit "
                         "(obs/metrics.py; docs/OBSERVABILITY.md §8)")
    qr.set_defaults(fn=cmd_query)

    ch = sub.add_parser(
        "chaos",
        help="infrastructure-fault conformance: inject each registered "
             "infra fault (SIGKILL, torn WAL, corrupt/bitflipped "
             "checkpoint, NaN-poisoned lane, admission storm) into a "
             "real subprocess run, doctor-assert the declared recovery "
             "signature, and require blame to name the planted fault "
             "at rank 1 (flow_updating_tpu.resilience.chaos, "
             "docs/RESILIENCE.md)")
    ch.add_argument("names", nargs="*", metavar="FAULT",
                    help="registered fault names (default: the whole "
                         "registry; see --list)")
    ch.add_argument("--list", action="store_true",
                    help="print the fault registry and exit")
    ch.add_argument("--nodes", type=int, default=128,
                    help="scripted-run member count")
    ch.add_argument("--lanes", type=int, default=8,
                    help="query-lane capacity for fabric faults")
    ch.add_argument("--segment-rounds", type=int, default=8)
    ch.add_argument("--ops", type=int, default=28,
                    help="scripted event-stream length (one WAL record "
                         "per op)")
    ch.add_argument("--seed", type=int, default=0)
    ch.add_argument("--outdir", default="obs-artifacts",
                    help="where the flow-updating-recovery-report/v1 "
                         "manifests land")
    ch.add_argument("--perturb", action="store_true",
                    help="negative control: disable the recovery "
                         "machinery — every signature is EXPECTED to "
                         "fail")
    ch.add_argument("--backend", default="auto",
                    choices=("auto", "cpu", "jax_tpu"),
                    help="JAX backend pin for the in-process "
                         "control/recovery runs (children always pin "
                         "cpu)")
    ch.set_defaults(fn=cmd_chaos)

    gen = sub.add_parser("generate", help="topology summary")
    _add_common(gen)
    gen.add_argument("--latency-scale", type=float, default=0.0)
    gen.set_defaults(fn=cmd_generate)

    orc = sub.add_parser("oracle", help="native DES reference-style run")
    _add_common(orc)
    orc.add_argument("--variant", default="collectall",
                     choices=("collectall", "pairwise"))
    orc.add_argument("--timeout", type=int, default=None)
    orc.add_argument("--ticks", type=int, default=1000)
    orc.add_argument("--latency-scale", type=float, default=0.0)
    orc.add_argument("--msg-bytes", type=float, default=104.0)
    orc.add_argument("--lmm", action="store_true",
                     help="dynamic max-min LMM network (SimGrid flow-"
                          "model fidelity; needs --platform and "
                          "--latency-scale > 0)")
    orc.set_defaults(fn=cmd_oracle)

    obs = sub.add_parser(
        "obs", help="observability tools (event-log trace export)")
    obs_sub = obs.add_subparsers(dest="obs_cmd", required=True)
    exp = obs_sub.add_parser(
        "export-trace",
        help="convert an --event-log JSONL into Chrome trace-event / "
             "Perfetto JSON: actor lanes, message-flow arrows, watcher "
             "metrics as counter tracks")
    exp.add_argument("eventlog", help="JSONL event log path")
    exp.add_argument("-o", "--output", default=None,
                     help="output path (default: <eventlog>.trace.json; "
                          "'-' = stdout)")
    exp.set_defaults(fn=cmd_obs_export_trace)

    pr = sub.add_parser(
        "profile",
        help="AOT cost attribution of the configured kernel's round "
             "program: XLA cost/memory analysis (flops, bytes accessed, "
             "peak memory), compile-vs-execute wall split, device "
             "memory stats and compile-cache counters — a pure "
             "observer, the plain program is untouched (obs/profile.py)")
    _add_common(pr)
    _add_kernel_flags(pr)
    pr.add_argument("--latency-scale", type=float, default=0.0,
                    help=">0: latency-warped delays from platform "
                         "latencies (as in `run`)")
    pr.add_argument("--rounds", type=int, default=64,
                    help="scan length to attribute (static — flops scale "
                         "with it; the per_round block amortizes)")
    pr.add_argument("--no-execute", action="store_true",
                    help="skip the timed execution (cost/memory + "
                         "compile split only)")
    pr.add_argument("--report", metavar="PATH",
                    help="write the flow-updating-profile-report/v1 "
                         "manifest (argv, config, topology fingerprint, "
                         "environment, attribution) to PATH")
    pr.add_argument("--roofline", action="store_true",
                    help="compose the cost record with the ambient "
                         "backend's hardware model (obs/roofline.py): "
                         "arithmetic intensity, binding resource, "
                         "predicted ceiling and the measured-vs-ceiling "
                         "roofline_frac ride the record (and the "
                         "manifest's flow-updating-perf-lens/v1 block "
                         "with --report)")
    pr.add_argument("--trace-dir", metavar="DIR",
                    help="also capture one round-program execution as a "
                         "device-timeline trace into DIR and measure "
                         "overlap_ratio from the actual wire/compute "
                         "slices (sharded halo paths; obs/timeline.py)")
    pr.set_defaults(fn=cmd_profile)

    ins = sub.add_parser(
        "inspect",
        help="topology-resolved observability: record per-node/per-edge "
             "metric fields on a live run (device-resident, "
             "stride/topk memory bounding), localize faults with "
             "--blame (straggler nodes, leaking edge pairs, divergence "
             "origins), diff two runs (--diff A B), render ASCII "
             "heatmaps over the topology (--heatmap FIELD) — "
             "flow-updating-field-report/v1 manifests (obs/fields.py, "
             "obs/inspect.py)")
    _add_common(ins)
    _add_kernel_flags(ins)
    ins.add_argument("reports", nargs="*", metavar="FIELDS.json",
                     help="saved field manifests to inspect")
    ins.add_argument("--latency-scale", type=float, default=0.0)
    ins.add_argument("--rounds", type=int, default=200,
                     help="live-run length (with --generator/"
                          "--deployment); must be a multiple of "
                          "--field-stride")
    ins.add_argument("--fields", nargs="?", const="default",
                     metavar="FIELDS",
                     help="field selection for the live run: 'default', "
                          "'full', or a comma list from: node_err, "
                          "node_mass, node_mass_residual, node_fired, "
                          "node_conv_round, edge_flow, edge_stale")
    ins.add_argument("--field-stride", type=int, default=1, metavar="K",
                     help="record every K-th round only (memory bound; "
                          "state evolution is unchanged)")
    ins.add_argument("--field-topk", type=int, default=0, metavar="M",
                     help="record only the M worst nodes per round "
                          "(ranked by |node_err|; single-device/GSPMD "
                          "kernels)")
    ins.add_argument("--conv-tol", type=float, default=1e-6,
                     help="per-node convergence-frontier tolerance for "
                          "node_conv_round")
    ins.add_argument("--rmse-threshold", type=float, default=1e-6,
                     help="stall-blame threshold: nodes above it with a "
                          "flat error trend rank as stragglers")
    ins.add_argument("--blame", action="store_true",
                     help="rank culprit node/edge ids per failing "
                          "symptom (stall stragglers, leaking edge "
                          "pairs, divergence origin)")
    ins.add_argument("--diff", nargs=2, metavar=("A.json", "B.json"),
                     help="align two field manifests on their common "
                          "round grid and report per-node/per-metric "
                          "deltas (identical-seed runs diff to zero)")
    ins.add_argument("--diff-atol", type=float, default=0.0,
                     help="absolute tolerance under which --diff "
                          "deltas count as identical")
    ins.add_argument("--heatmap", metavar="FIELD",
                     help="render FIELD as an ASCII heatmap over the "
                          "generator's coordinates (plain text output)")
    ins.add_argument("--heatmap-round", type=int, default=-1,
                     help="recorded row to render (default: last)")
    ins.add_argument("--heatmap-width", type=int, default=64,
                     help="wrap width when the topology has no "
                          "coordinates")
    ins.add_argument("--report", metavar="PATH",
                     help="write the live run's "
                          "flow-updating-field-report/v1 manifest to "
                          "PATH")
    ins.add_argument("-o", "--output", default=None, metavar="PATH",
                     help="write the JSON result (summary/blame/diff) "
                          "to PATH instead of stdout")
    ins.set_defaults(fn=cmd_inspect)

    pl = sub.add_parser(
        "plan",
        help="topology compiler standalone: compile any graph into an "
             "RCM-band + remainder execution plan, print the chosen "
             "kernel/spmv with band occupancy and predicted cost "
             "(--explain for the human-readable breakdown), write the "
             "flow-updating-plan-report/v1 manifest (--report) — "
             "flow_updating_tpu.plan, docs/PLANNER.md")
    _add_common(pl)
    _add_kernel_flags(pl)
    pl.add_argument("--plan-backend", default=None,
                    choices=("tpu", "cpu"),
                    help="rank candidates for this backend's cost model "
                         "instead of the ambient one (a TPU plan can be "
                         "inspected from a CPU session)")
    pl.add_argument("--probe", action="store_true",
                    help="rank candidates by XLA's own cost_analysis of "
                         "the lowered programs (obs/profile.py AOT) "
                         "instead of the analytic HBM-traffic model — "
                         "compiles each candidate once")
    pl.add_argument("--autotune", action="store_true",
                    help="time the banded-family candidates (band width "
                         "x fused-round tile x remainder route) on the "
                         "ambient device and rank from MEASURED rates; "
                         "results persist in the autotune cache keyed "
                         "by (plan hash, backend, jax version), so a "
                         "warm cache re-ranks with zero probes")
    pl.add_argument("--max-lanes", type=int, default=96,
                    help="dense roll-lane budget (each kept diagonal "
                         "costs one streamed pass per neighbor sum)")
    pl.add_argument("--min-fill", type=float, default=None,
                    help="occupancy floor for keeping a diagonal as a "
                         "band lane, as a fraction of N (default: the "
                         "backend's break-even 3/gather_cost)")
    pl.add_argument("--remainder", default="auto",
                    choices=("auto", "gather", "benes", "none"),
                    help="route for out-of-band edges: Benes permutation "
                         "lanes (gather-free, the TPU form), plain "
                         "bucketed ELL gather, or refuse any remainder")
    pl.add_argument("--explain", action="store_true",
                    help="print the human-readable decision breakdown "
                         "(band occupancy table, predicted costs) to "
                         "stderr alongside the JSON")
    pl.add_argument("--mixing", action="store_true",
                    help="estimate the diffusion operator's spectral "
                         "gap (power iteration + decay probe riding "
                         "the selected lowering, autotune-cached) and "
                         "embed the mixing block: gap, provenance, "
                         "predicted rounds-to-eps "
                         "(docs/OBSERVABILITY.md §10)")
    pl.add_argument("--report", metavar="PATH",
                    help="write the flow-updating-plan-report/v1 "
                         "manifest to PATH")
    pl.set_defaults(fn=cmd_plan)

    sc = sub.add_parser(
        "scenarios",
        help="adversarial conformance suite: run registered scenarios "
             "(conductance-bottleneck bridges, Byzantine nodes, "
             "correlated failures) under the sweep engine, blame the "
             "planted adversary, and assert each declared signature — "
             "flow-updating-scenario-report/v1 manifests "
             "(flow_updating_tpu.scenarios; agg_* names run the "
             "per-kind aggregate fault cases, docs/AGGREGATES.md)")
    sc.add_argument("names", nargs="*", metavar="SCENARIO",
                    help="registered scenario names (default: the whole "
                         "registry; see --list)")
    sc.add_argument("--list", action="store_true",
                    help="print the registry (name, summary, config, "
                         "declared signature) and exit")
    sc.add_argument("--seeds", type=int, default=2, metavar="K",
                    help="seeds per scenario (the sweep grid width)")
    sc.add_argument("--seed", type=int, default=0,
                    help="base seed (seeds are seed..seed+K-1)")
    sc.add_argument("--perturb",
                    choices=("remove_adversary", "no_heal"),
                    help="negative control: withdraw the planted fault "
                         "(or never heal the partition) — signatures "
                         "are EXPECTED to fail")
    sc.add_argument("--max-batch", type=int, default=0, metavar="B",
                    help="cap sweep lanes per compiled bucket (0 = "
                         "unbounded)")
    sc.add_argument("--report", metavar="PATH",
                    help="write the flow-updating-scenario-report/v1 "
                         "manifest to PATH")
    sc.add_argument("--strict", action="store_true",
                    help="exit 1 on warnings too")
    sc.add_argument("--backend", default="auto",
                    choices=("auto", "cpu", "jax_tpu"),
                    help="JAX backend pin (cpu deregisters TPU "
                         "factories)")
    sc.set_defaults(fn=cmd_scenarios)

    dr = sub.add_parser(
        "doctor",
        help="rule-based health verdicts with evidence: NaN/divergence "
             "watchdog, RMSE-stall detection, mass-conservation and "
             "antisymmetry drift, environment sanity, recorded-baseline "
             "validity — on saved manifests and/or a live telemetry "
             "run; exit 1 on any fail (obs/health.py)")
    _add_common(dr)
    _add_kernel_flags(dr)
    dr.add_argument("reports", nargs="*", metavar="REPORT.json",
                    help="saved flow-updating-*-report/v1 manifests to "
                         "judge")
    dr.add_argument("--latency-scale", type=float, default=0.0)
    dr.add_argument("--rounds", type=int, default=200,
                    help="live-run length (with --generator/"
                         "--deployment)")
    dr.add_argument("--rmse-threshold", type=float, default=1e-6,
                    help="convergence threshold for the stall check")
    dr.add_argument("--baselines", nargs="?",
                    const="BASELINE_MEASURED.json", metavar="PATH",
                    help="audit recorded DES baselines against the "
                         "spread validity gate (default file: "
                         "BASELINE_MEASURED.json)")
    dr.add_argument("--golden", nargs="?", const="GOLDEN_PROGRAMS.json",
                    metavar="PATH",
                    help="program_conformance: audit the golden-program "
                         "ledger (default file: GOLDEN_PROGRAMS.json)")
    dr.add_argument("--strict", action="store_true",
                    help="warnings also exit 1")
    dr.set_defaults(fn=cmd_doctor)

    rg = sub.add_parser(
        "regress",
        help="perf regression gate: compare a fresh bench result line "
             "or profile manifest against the BENCH_* artifact history "
             "/ a reference manifest, flagging drops beyond the "
             "recorded spread; exit 1 on regression (obs/regress.py)")
    rg.add_argument("--fresh", required=True, metavar="PATH",
                    help="fresh bench JSON line or profile manifest")
    rg.add_argument("--against", metavar="PATH",
                    help="reference profile manifest to compare against")
    rg.add_argument("--history", default="BENCH_*.json", metavar="GLOB",
                    help="bench artifact history (default: BENCH_*.json "
                         "in the working directory)")
    rg.add_argument("--margin", type=float, default=None, metavar="PCT",
                    help="override the allowed drop/growth percentage")
    rg.set_defaults(fn=cmd_regress)

    ln = sub.add_parser(
        "lint",
        help="repo-specific static analysis: AST rules ruff cannot "
             "express (numpy in kernels, traced `if`, kernel "
             "round_program coverage, bare PRNGKey, baseline key "
             "families, zero-copy device arrays over mutated host "
             "mirrors) + the jaxpr rule engine over every kernel's "
             "round program (serializing scatters, fast-path gathers, "
             "callbacks/collectives in the round scan, dtype drift, "
             "PRNG key reuse); exit 1 on any finding "
             "(flow_updating_tpu/analysis)")
    ln.add_argument("paths", nargs="*", metavar="PATH",
                    help="files to lint (default: the whole repo "
                         "surface; an explicit list skips the jaxpr "
                         "kernel matrix)")
    ln.add_argument("--ast-only", action="store_true",
                    help="skip the jaxpr rule engine (no jax import)")
    ln.set_defaults(fn=cmd_lint)

    au = sub.add_parser(
        "audit",
        help="golden-program conformance: re-lower every (mode x twin "
             "x robust x adversary x payload) cell and diff against the "
             "committed GOLDEN_PROGRAMS.json ledger, naming the exact "
             "cell and first divergent HLO line on drift; exit 1 on "
             "drift (flow_updating_tpu/analysis/golden.py)")
    au.add_argument("--ledger", default="GOLDEN_PROGRAMS.json",
                    metavar="PATH", help="ledger file location")
    au.add_argument("--rebase", action="store_true",
                    help="regenerate the ledger from the current "
                         "lowerings (after an INTENTIONAL program "
                         "change; review the diff)")
    au.add_argument("--report", metavar="PATH",
                    help="write a flow-updating-audit-report/v1 "
                         "manifest (doctor judges it)")
    au.add_argument("--no-invariants", action="store_true",
                    help="skip the semantic invariant prover "
                         "(antisymmetry pairing / clip symmetry / mask "
                         "neutrality / observer purity over every "
                         "registered cell — analysis/invariants.py; on "
                         "by default, trace-only)")
    au.add_argument("--budget", metavar="PATH",
                    help="also run the collective/wire-byte budget "
                         "verifier (compiled HLO collective bytes vs "
                         "plan accounting ±5%%, unbudgeted collectives "
                         "named — analysis/budget.py) and write the "
                         "flow-updating-budget-report/v1 manifest here "
                         "(doctor judges it; regress --against gates "
                         "byte growth)")
    au.add_argument("--strict", action="store_true",
                    help="environment-mismatch warnings also exit 1")
    au.set_defaults(fn=cmd_audit)

    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # the run manifest records the exact invocation
    args._argv = list(argv) if argv is not None else list(sys.argv[1:])
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(levelname)s %(name)s: %(message)s",
    )
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
