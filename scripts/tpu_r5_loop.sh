#!/bin/sh
# Round-5 TPU contact loop: probe (wedge-safe, 290 s budget inside the
# session script) every 15 min until the tunnel answers, then run the
# full banked session.  rc=3 means probe-failed (keep looping); rc=4
# means the canary found no live-TPU rows (likely mid-recovery wedge:
# back off longer, retry); rc=0 means the session ran to completion.
# Any OTHER rc is a permanent failure (crash, usage error, missing
# interpreter) — exit and surface it instead of retrying for days.
cd "$(dirname "$0")/.." || exit 1
i=0
while :; do
    i=$((i + 1))
    echo "== attempt $i: $(date -u +%FT%TZ)" >> _r5_session_loop.log
    python scripts/tpu_r5_session.py >> _r5_session_loop.log 2>&1
    rc=$?
    echo "== attempt $i exited rc=$rc" >> _r5_session_loop.log
    case "$rc" in
        0) echo "session complete" >> _r5_session_loop.log; exit 0 ;;
        3) sleep 900 ;;
        4) sleep 1800 ;;
        *) echo "unexpected rc=$rc — stopping (see log)" \
               >> _r5_session_loop.log; exit "$rc" ;;
    esac
done
