#!/usr/bin/env python
"""Telemetry-off must cost nothing: the regression gate.

The observability contract (docs/OBSERVABILITY.md) promises that a run
with telemetry DISABLED compiles to the exact pre-telemetry program.
This script enforces it three ways on the CPU backend:

1. **program identity** — ``Engine.run_telemetry`` with a disabled spec
   advances state bit-identically to the plain kernel;
2. **in-run rate parity** — the disabled-telemetry round rate matches the
   plain kernel's, measured back to back (same machine state), within
   ``--threshold`` percent;
3. **baseline gate** — the disabled-telemetry rate is within
   ``--threshold`` percent of the recorded ``k<K>`` CPU round rate in
   BASELINE_MEASURED.json (``cpu_telemetry_off`` field; recorded on first
   run, refreshed upward under keep-fastest).

It also measures telemetry-ON so the enabled-path overhead is visible in
the output (informational — enabling telemetry legitimately adds
reductions).

Exit code 0 = all gates pass.  Usage::

    python scripts/telemetry_overhead.py            # k=96, the baseline
    python scripts/telemetry_overhead.py --k 16     # quick CI check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MEASURED_PATH = os.path.join(REPO, "BASELINE_MEASURED.json")


def _scan_diff(run, rounds: int) -> float:
    """Seconds of pure scan work: 2R-launch minus R-launch (launch
    overhead and dispatch cost cancel)."""
    t0 = time.perf_counter()
    run(rounds)
    t_r = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(2 * rounds)
    t_2r = time.perf_counter() - t0
    return t_2r - t_r


def measure_paired(runs: dict, rounds: int, repeats: int = 5):
    """Per-path round rates measured INTERLEAVED: each repeat times every
    path back to back, so a machine-contention spike hits all of them,
    not whichever happened to run second.  Per path the best (smallest)
    diff wins — the repo's keep-fastest convention (bench.py) — and the
    regression gate compares those bests.  The scan grows until the
    reference path's diff clears timer noise.  Returns
    ``({name: rounds_per_sec}, rounds_used)``."""
    # the timed difference must dwarf launch jitter (GC, page faults on
    # multi-MB host reads): an A/A calibration on this measurement showed
    # ±20% spread at 0.05s diffs, ±2% at 0.5s
    min_diff_s = 0.5
    ref = next(iter(runs.values()))
    while True:
        ref(rounds)
        ref(2 * rounds)
        if _scan_diff(ref, rounds) > min_diff_s or rounds >= 262144:
            break
        rounds *= 4
    best: dict = {}
    for name, run in runs.items():
        run(rounds)        # warm this path's compilations at both lengths
        run(2 * rounds)
    for _ in range(repeats):
        for name, run in runs.items():
            d = _scan_diff(run, rounds)
            if d > 0 and (name not in best or d < best[name]):
                best[name] = d
    return {name: rounds / max(best.get(name, 1e-9), 1e-9)
            for name in runs}, rounds


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=96,
                    help="fat-tree arity (96 -> ~233k nodes, the recorded "
                         "baseline config)")
    ap.add_argument("--rounds", type=int, default=32,
                    help="timed scan length (R; the rate uses R vs 2R)")
    ap.add_argument("--threshold", type=float, default=5.0,
                    help="max tolerated regression, percent")
    ap.add_argument("--no-record", action="store_true",
                    help="never write BASELINE_MEASURED.json")
    args = ap.parse_args()

    from flow_updating_tpu.utils.backend import pin_cpu

    pin_cpu()
    import numpy as np

    from flow_updating_tpu.engine import Engine
    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.obs.telemetry import TelemetrySpec
    from flow_updating_tpu.topology.generators import fat_tree

    topo = fat_tree(args.k, seed=0)
    cfg = RoundConfig.fast(variant="collectall", kernel="node")
    result = {"k": args.k, "nodes": topo.num_nodes,
              "edges": topo.num_edges, "rounds": args.rounds,
              "threshold_pct": args.threshold}
    failures = []

    # 1. program identity: off-path state == plain kernel state, AND
    # the plain round program lowers byte-identically before/after the
    # telemetry dispatch exists — via the one canonicalizer every
    # program-identity assert routes through (analysis/golden.py)
    from flow_updating_tpu.analysis import golden

    kern = sync.NodeKernel(topo, cfg)
    fn, fargs, _nd = kern.round_program(kern.init_state(), 8)
    text_before = golden.canonical_program(fn, *fargs)
    plain_out = kern.run(kern.init_state(), 8)
    eng = Engine(config=cfg).set_topology(topo).build()
    eng.run_telemetry(8, TelemetrySpec.off())
    if not np.array_equal(np.asarray(plain_out.G),
                          np.asarray(eng.state.G)):
        failures.append("telemetry-off state diverges from the plain "
                        "kernel (the off path must be the SAME program)")
    if golden.canonical_program(fn, *fargs) != text_before:
        failures.append("telemetry dispatch perturbed the plain round "
                        "program's lowering (off must be the SAME "
                        "program)")
    result["program_identical"] = not failures

    # 2. rates: plain kernel, telemetry-off dispatch, telemetry-on
    state = kern.init_state()

    def run_plain(r):
        out = kern.run(state, r)
        np.asarray(out.G[:1])

    spec_on = TelemetrySpec.default().for_kernel("node")

    def run_on(r):
        _, series = kern.run_telemetry(state, r, spec_on)
        np.asarray(series["rmse"][:1])

    eng_off = Engine(config=cfg).set_topology(topo).build()
    init0 = eng_off.state

    def run_off(r):
        # restart from the initial state every launch, like the other two
        # paths: a state that converged over prior launches hits subnormal
        # arithmetic (orders slower on x86) and would misread as dispatch
        # overhead
        eng_off.state = init0
        eng_off.run_telemetry(r, TelemetrySpec.off())
        np.asarray(eng_off.state.G[:1])

    rates, used = measure_paired(
        {"plain": run_plain, "off": run_off, "on": run_on}, args.rounds)
    plain_rps, off_rps, on_rps = rates["plain"], rates["off"], rates["on"]
    result["rounds_timed"] = used
    result["plain_rounds_per_sec"] = round(plain_rps, 3)
    result["telemetry_off_rounds_per_sec"] = round(off_rps, 3)
    result["telemetry_on_rounds_per_sec"] = round(on_rps, 3)
    result["telemetry_on_overhead_pct"] = round(
        100.0 * (plain_rps - on_rps) / plain_rps, 1)

    off_reg = 100.0 * (plain_rps - off_rps) / plain_rps
    result["off_vs_plain_regression_pct"] = round(off_reg, 2)
    if off_reg > args.threshold:
        failures.append(
            f"telemetry-off path is {off_reg:.1f}% slower than the plain "
            f"kernel (threshold {args.threshold}%)")

    # 3. recorded-baseline gate (BASELINE_MEASURED.json k<K>)
    key = f"k{args.k}"
    data = {}
    try:
        with open(MEASURED_PATH) as f:
            data = json.load(f)
    except Exception:
        pass
    recorded = data.get(key, {}).get("cpu_telemetry_off", {})
    base_rps = recorded.get("rounds_per_sec")
    if base_rps:
        vs_base = 100.0 * (base_rps - off_rps) / base_rps
        result["baseline_rounds_per_sec"] = round(base_rps, 3)
        result["off_vs_baseline_regression_pct"] = round(vs_base, 2)
        if vs_base > args.threshold:
            failures.append(
                f"telemetry-off rate regressed {vs_base:.1f}% vs the "
                f"recorded {key} baseline (threshold {args.threshold}%)")
    # keep-fastest record (mirrors bench.py record semantics: the record
    # is the best observed machine state, never degraded by a slow run)
    if not args.no_record and off_rps > (base_rps or 0.0):
        entry = data.setdefault(key, {})
        entry.setdefault("nodes", topo.num_nodes)
        entry.setdefault("edges", topo.num_edges)
        entry["cpu_telemetry_off"] = {
            # the ACTUAL timed scan length (adaptively grown), not the
            # requested starting point — a reproduction must use this
            "rounds_per_sec": off_rps, "rounds": used,
            "kernel": "node",
        }
        try:
            with open(MEASURED_PATH, "w") as f:
                json.dump(data, f, indent=1)
            result["recorded"] = True
        except OSError:
            pass

    result["ok"] = not failures
    if failures:
        result["failures"] = failures
    print(json.dumps(result))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
