#!/usr/bin/env python
"""One-contact TPU measurement session for round 5.

Same tunnel discipline as round 4 (scripts/tpu_r4_session.py): a
successful probe must be exploited immediately, in VERDICT-r4 priority
order, banking each result to a repo artifact the moment it exists.
Every step is a sequential subprocess with NO timeout — timeout-killing
a mid-compile TPU process is what wedges the tunnel for hours.

Round-5 priority order (VERDICT r4 "Next round" items):

  1. micro96        — cheap canary (Mosaic compile health at 233k nodes)
                      + fresh structured/benes k=96 rows for this round.
  2. edge96_fused   — item 1: the faithful asynchronous path with fused
                      segment circuits; target >= the 332.49 r/s DES
                      k96_faithful baseline of record.
  3. configs        — item 2: ER-10k (collect-all + fast pairwise) and
                      BA-100k rows, the non-fat-tree BASELINE.json
                      configs.
  4. megascale      — item 3: the 1M -> 66M virtual-fat-tree ladder
                      (replaces the ~330 r/s projection with numbers).
  5. profile160     — item 4: per-phase round attribution (the r4
                      artifact is an rc-1 failure).
  6. pairwise96     — item 7: fast pairwise at k=96 vs a live pairwise
                      DES baseline.
  7. bench          — the full r5 headline (BENCH_TPU_r5.json).
  8. edge160_fused  — item 1 stretch: a faithful row at headline scale.
  9. micro160       — refresh the k=160 spmv table under r5.

Usage: python scripts/tpu_r5_session.py [--skip-probe] [--steps ...]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

MICRO_ART = "MICROBENCH_TPU_r5.json"


def _session_env() -> dict:
    """Child env: persistent XLA compilation cache shared across the
    session's processes — big fused-path compiles are paid once."""
    env = dict(os.environ)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/flow_updating_tpu/xla"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    return env


def _run(cmd: list[str], log_name: str) -> tuple[int, str]:
    """Run to completion (NO timeout — see module doc), tee to a log."""
    log_path = os.path.join(REPO, f"_tpu_session_{log_name}.log")
    t0 = time.time()
    with open(log_path, "w") as lf:
        p = subprocess.run(cmd, cwd=REPO, stdout=lf,
                           stderr=subprocess.STDOUT, env=_session_env())
    out = open(log_path).read()
    print(f"[{log_name}] rc={p.returncode} {time.time()-t0:.0f}s "
          f"({len(out)}B log)", flush=True)
    return p.returncode, out


def _json_lines(text: str) -> list[dict]:
    rows = []
    for ln in text.splitlines():
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
    return rows


def _bank(path: str, payload) -> None:
    with open(os.path.join(REPO, path), "w") as f:
        json.dump(payload, f, indent=1)
    print(f"banked {path}", flush=True)


def probe() -> bool:
    sys.path.insert(0, REPO)
    from bench import _probe_tpu

    status, detail = _probe_tpu()
    print(f"probe: {status} ({detail})", flush=True)
    return status == "ok"


ALL_STEPS = ("micro96", "edge96_fused", "configs", "megascale",
             "profile160", "pairwise96", "bench", "edge160_fused",
             "micro160", "micro40", "edge96")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-probe", action="store_true")
    ap.add_argument("--steps", default=",".join(ALL_STEPS[:9]),
                    help="comma-separated subset in run order (a follow-up "
                         "contact after a mid-session wedge should list "
                         "only the not-yet-banked steps)")
    args = ap.parse_args()
    steps = [s.strip() for s in args.steps.split(",") if s.strip()]
    unknown = set(steps) - set(ALL_STEPS)
    if unknown:
        ap.error(f"unknown steps {sorted(unknown)}; have {ALL_STEPS}")

    if not args.skip_probe and not probe():
        return 3

    session: dict = {"started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime()),
                     "steps": {}}
    # a follow-up session merges into the already-banked artifact rather
    # than discarding the earlier contact's measurements
    micro_path = os.path.join(REPO, MICRO_ART)
    if os.path.exists(micro_path):
        try:
            with open(micro_path) as f:
                banked = json.load(f)
            if isinstance(banked, dict):
                session["steps"].update(banked.get("steps", banked))
        except (OSError, json.JSONDecodeError):
            pass

    def _keep(step: str, record: dict, good: bool) -> None:
        """Bank a step's result — but never let a failed or degraded
        re-run clobber a previously banked success."""
        prior = session["steps"].get(step)
        if good or not prior:
            session["steps"][step] = record
        _bank(MICRO_ART, session["steps"])

    def _tpu_rows(rc: int, rows: list) -> bool:
        """Clean exit AND rows measured on the TPU — a CPU-run microbench
        (silent backend fallback) must not displace banked TPU rows."""
        return rc == 0 and bool(rows) and all(
            r.get("platform") == "tpu" for r in rows)

    def _bench_step(step: str, extra: list[str],
                    bank_headline: bool = False) -> None:
        """One bench.py invocation; bank only live-TPU ok results as
        good, and optionally carry the full headline artifact."""
        rc, out = _run([PY, "bench.py", *extra], step)
        rows = _json_lines(out)
        live = bool(rows) and rows[-1].get("backend") == "tpu" \
            and bool(rows[-1].get("ok"))
        if live and bank_headline:
            _bank("BENCH_TPU_r5.json", rows[-1])
        _keep(step, {"rc": rc, "result": rows[-1] if rows else None}, live)

    # -- 1. canary at k=96 (retry once: transient helper SIGKILLs) -------
    if "micro96" in steps:
        for attempt in (1, 2):
            rc, out = _run([PY, "scripts/tpu_microbench.py", "--spmv", "96"],
                           f"micro96_a{attempt}")
            rows = _json_lines(out)
            if _tpu_rows(rc, rows):
                break
        _keep("micro96", {"rc": rc, "rows": rows}, _tpu_rows(rc, rows))
        if not _tpu_rows(rc, rows):
            # a clean rc with CPU rows is the silent-backend-fallback case:
            # the TPU is gone, every later step would burn the contact on
            # unbankable degraded runs — stop and let the loop back off
            print("canary failed twice (no live-TPU rows) — banking what "
                  "exists and stopping before a wedged tunnel eats the "
                  "session", flush=True)
            return 4

    # -- 2. faithful asynchronous path, fused circuits (VERDICT item 1) --
    if "edge96_fused" in steps:
        _bench_step("edge96_fused",
                    ["--kernel", "edge", "--fire-policy", "reference",
                     "--fat-tree-k", "96", "--skip-des",
                     "--skip-convergence",
                     "--segment", "benes_fused",
                     "--delivery", "benes_fused"])

    # -- 3. ER-10k / BA-100k config rows (VERDICT item 2) ----------------
    if "configs" in steps:
        rc, out = _run([PY, "scripts/tpu_microbench.py", "--configs"],
                       "configs")
        rows = _json_lines(out)
        good = rc == 0 and bool(rows) \
            and rows[-1].get("platform") == "tpu" \
            and all("error" not in r for r in rows[-1].get("rows", []))
        _keep("configs", {"rc": rc,
                          "result": rows[-1] if rows else None}, good)

    # -- 4. mega-scale virtual-fat-tree ladder (VERDICT item 3) ----------
    # banks its own artifact progressively (MEGASCALE_TPU_r5.json) and
    # refuses to bank non-TPU rows itself (exit 2 on a CPU backend)
    if "megascale" in steps:
        rc, out = _run([PY, "scripts/tpu_megascale.py"], "megascale")
        _keep("megascale", {"rc": rc}, rc == 0)

    # -- 5. per-round attribution (VERDICT item 4) -----------------------
    if "profile160" in steps:
        rc, out = _run([PY, "scripts/tpu_profile_round.py", "--k", "160"],
                       "profile160")
        rows = _json_lines(out)
        good = rc == 0 and bool(rows)
        _keep("profile160", {"rc": rc, "rows": rows}, good)
        if good or not os.path.exists(os.path.join(REPO,
                                                   "PROFILE_TPU_r5.json")):
            _bank("PROFILE_TPU_r5.json", session["steps"]["profile160"])

    # -- 6. fast pairwise at scale (VERDICT item 7) ----------------------
    # measures its own live pairwise DES baseline (timeout=1, like-for-
    # like with the matching-gossip fast mode); records k96_pairwise
    if "pairwise96" in steps:
        _bench_step("pairwise96",
                    ["--kernel", "edge", "--variant", "pairwise",
                     "--fat-tree-k", "96", "--skip-convergence",
                     "--segment", "benes_fused"])

    # -- 7. full r5 headline ---------------------------------------------
    if "bench" in steps:
        _bench_step("bench", [], bank_headline=True)

    # -- 8. faithful fused at headline scale (item 1 stretch) ------------
    if "edge160_fused" in steps:
        _bench_step("edge160_fused",
                    ["--kernel", "edge", "--fire-policy", "reference",
                     "--fat-tree-k", "160", "--skip-convergence",
                     "--segment", "benes_fused",
                     "--delivery", "benes_fused"])

    # -- 9+. spmv tables refresh -----------------------------------------
    for step, karg in (("micro160", "160"), ("micro40", "40"),
                       ("edge96", None)):
        if step not in steps:
            continue
        if karg is not None:
            rc, out = _run([PY, "scripts/tpu_microbench.py", "--spmv", karg],
                           step)
            rows = _json_lines(out)
            _keep(step, {"rc": rc, "rows": rows}, _tpu_rows(rc, rows))
        else:  # unfused faithful comparison row
            _bench_step("edge96", ["--kernel", "edge", "--fire-policy",
                                   "reference", "--fat-tree-k", "96",
                                   "--skip-des", "--skip-convergence"])

    print("session complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
