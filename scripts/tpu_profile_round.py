#!/usr/bin/env python
"""Attribute the node-kernel round's per-round cost (VERDICT r4 item 2).

Decomposes ms/round at a given scale into:
  * the SpMV alone (the permutation network / gather — the suspected
    dominant term; BENCH_NOTES "TPU per-round cost accounting"),
  * the elementwise recurrence alone (avg/S/G updates with the SpMV
    replaced by identity — the HBM-stream floor),
  * the full round (their fusion; gaps vs sum = launch/scheduling),
all via the R-vs-2R chained-scan difference under the tunnel launch cap,
and optionally records a ``jax.profiler`` trace of one chunk
(``--trace DIR``) for op-level drill-down.

Writes one JSON line per (spmv, part) to stdout; bank the output into
PROFILE_TPU_r4.json when run live.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MAX_LAUNCH_S = 20.0


def _time_chain(step, state, aux, r0: int):
    """seconds/iteration of ``step(carry, aux)`` via scan-chain R-vs-2R
    difference.  ``aux`` (the kernel's constant arrays) is a jit ARGUMENT,
    not a closure capture — captured jnp arrays embed as HLO constants and
    the 25 MB ELL mats at k=160 blow the tunnel's remote_compile request
    cap (observed: HTTP 413)."""
    import jax
    import numpy as np

    @functools.partial(jax.jit, static_argnames="n")
    def chain(s, a, n):
        return jax.lax.scan(lambda c, _: (step(c, a), None), s, None,
                            length=n)[0]

    def run(n):
        out = chain(state, aux, n)
        np.asarray(jax.tree.leaves(out)[0].ravel()[:2])  # force completion

    r = r0
    while True:
        run(r)
        run(2 * r)
        t0 = time.perf_counter(); run(r); t1 = time.perf_counter()
        run(2 * r); t2 = time.perf_counter()
        if (t2 - t1) - (t1 - t0) > 0.05 or (t2 - t1) * 8 > MAX_LAUNCH_S:
            break
        r *= 8
    return max(((t2 - t1) - (t1 - t0)) / r, 1e-9), r


def profile(k: int, spmv: str, trace_dir: str | None) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.topology.generators import fat_tree

    topo = fat_tree(k, seed=0)
    cfg = RoundConfig.fast(variant="collectall", kernel="node", spmv=spmv)
    kern = sync.NodeKernel(topo, cfg)
    st = kern.init_state()
    arrs = kern.arrays
    rows = []

    def emit(part, step, carrier, r0=32):
        per_s, r = _time_chain(step, carrier, arrs, r0)
        row = {"k": k, "nodes": topo.num_nodes, "spmv": spmv, "part": part,
               "ms_per_iter": round(per_s * 1e3, 4), "iters_timed": r,
               "platform": jax.devices()[0].platform}
        rows.append(row)
        print(json.dumps(row), flush=True)

    # 1. full round
    emit("full_round", lambda s, a: sync.node_round_step(s, a, cfg), st)

    # 2. SpMV alone (same input shape/dtype as the round feeds it)
    x0 = st.avg_prev + jnp.asarray(0, st.avg_prev.dtype)
    if spmv in ("benes", "benes_fused"):
        from flow_updating_tpu.ops.spmv_benes import neighbor_sum_benes

        emit("spmv_only",
             lambda x, a: neighbor_sum_benes(x, a.ns_plan, a.ns_masks),
             x0)
    elif spmv == "structured":
        from flow_updating_tpu.ops.structured import structured_neighbor_sum

        emit("spmv_only",
             lambda x, a: structured_neighbor_sum(x, a.ns_struct), x0)
    else:
        emit("spmv_only", lambda x, a: sync.neighbor_sum(x, a.mats), x0)

    # 3. elementwise recurrence with the SpMV cut out (A := avg): the
    #    pure O(N)-stream floor of the round
    def elementwise_only(s, a):
        avg = (a.value - s.S + s.A_prev) * a.inv_depp1
        A_cur = avg
        return s.replace(t=s.t + 1, S=-s.G - A_cur + a.deg * s.avg_prev,
                         G=-s.S - a.deg * avg + s.A_prev,
                         avg_prev=avg, A_prev=A_cur)

    emit("elementwise_only", elementwise_only, st, r0=256)

    if trace_dir:
        import numpy as np

        with jax.profiler.trace(trace_dir):
            out = kern.run(st, 16)
            np.asarray(out.S[:2])
        print(json.dumps({"trace": trace_dir, "spmv": spmv, "rounds": 16}),
              flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=160)
    ap.add_argument("--spmv", default="structured,benes_fused,benes,xla",
                    help="comma list; order = measurement order")
    ap.add_argument("--trace", default=None,
                    help="profiler trace output dir (one chunk per spmv)")
    args = ap.parse_args()
    for s in args.spmv.split(","):
        td = os.path.join(args.trace, s) if args.trace else None
        profile(args.k, s.strip(), td)
    return 0


if __name__ == "__main__":
    sys.exit(main())
