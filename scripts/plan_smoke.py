#!/usr/bin/env python
"""CI planner smoke: compile, run, bit-parity, manifest, doctor.

Exercises the topology compiler (flow_updating_tpu.plan) end to end on
CPU with a small BA graph and leaves the plan manifest in ``--outdir``
(the tier1 workflow uploads it next to the observability manifests):

1. ``compile_topology`` on a Barabasi-Albert graph — the plan must
   cover every edge (bands + remainder) and its banded neighbor sum
   must equal the adjacency sum BIT-FOR-BIT on an integer payload;
2. a planned edge-kernel run (stable RCM relabeling) must evolve
   bit-for-bit like the original-order kernel after unpermutation;
3. ``Engine(plan='auto')`` must run and agree with the plain edge
   engine to float tolerance;
4. the ``plan`` CLI writes a ``flow-updating-plan-report/v1`` manifest,
   judged by ``doctor`` (exit 1 on any failing check).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--generator", default="barabasi_albert:500:3",
                    help="smoke topology")
    ap.add_argument("--rounds", type=int, default=80)
    args = ap.parse_args()
    os.makedirs(args.outdir, exist_ok=True)

    import numpy as np

    from flow_updating_tpu.utils.backend import pin_cpu

    pin_cpu()
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.engine import Engine
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.models.rounds import node_estimates, run_rounds
    from flow_updating_tpu.models.state import init_state
    from flow_updating_tpu.plan import banded_neighbor_sum, compile_topology

    name, *params = args.generator.split(":")
    from flow_updating_tpu.topology.generators import GENERATORS

    topo = GENERATORS[name](*[int(p) for p in params], seed=0)
    plan = compile_topology(topo)

    # 1. banded neighbor sum == adjacency sum, bit-for-bit (int payload)
    x = np.arange(1, topo.num_nodes + 1, dtype=np.float64)[plan.order]
    got = np.asarray(banded_neighbor_sum(jnp.asarray(x), plan.spmv,
                                         plan.leaves))
    ref = np.zeros(topo.num_nodes)
    np.add.at(ref, plan.topo.src, x[plan.topo.dst])
    if not np.array_equal(got, ref):
        print("plan_smoke: banded neighbor sum is NOT bit-exact "
              f"(max delta {np.abs(got - ref).max()})", file=sys.stderr)
        return 1

    # 2. planned edge run bit-parity vs original order
    cfg = RoundConfig.fast(variant="collectall", dtype="float64")
    est = np.asarray(node_estimates(
        run_rounds(init_state(topo, cfg), topo.device_arrays(), cfg,
                   args.rounds), topo.device_arrays()))
    est_p = np.asarray(node_estimates(
        run_rounds(init_state(plan.topo, cfg), plan.topo.device_arrays(),
                   cfg, args.rounds), plan.topo.device_arrays()))
    if not np.array_equal(plan.unpermute_nodes(est_p), est):
        print("plan_smoke: planned edge run is NOT bit-exact",
              file=sys.stderr)
        return 1

    # 3. one auto-planned Engine run, tolerance-checked vs the edge est
    eng = Engine(config=cfg, plan="auto").set_topology(topo).build()
    eng.run_rounds(args.rounds)
    if not np.allclose(eng.estimates(), est, rtol=1e-9, atol=1e-9):
        print("plan_smoke: Engine(plan='auto') diverged from the edge "
              "kernel", file=sys.stderr)
        return 1

    # 3b. the ONE-KERNEL fused round (spmv='banded_fused', Pallas
    # interpret mode on this CPU run) must reproduce the unfused banded
    # executor BIT-for-bit over a multi-round evolution — the shipped
    # kernel is the tested kernel (tier-1 gate)
    import dataclasses

    from flow_updating_tpu.models import sync

    cfg_node = RoundConfig.fast(variant="collectall", dtype="float64",
                                kernel="node", spmv="banded")
    kb = sync.NodeKernel(topo, cfg_node, plan=plan)
    kf = sync.NodeKernel(
        topo, dataclasses.replace(cfg_node, spmv="banded_fused"),
        plan=plan)
    est_b = kb.estimates(kb.run(kb.init_state(), args.rounds))
    est_f = kf.estimates(kf.run(kf.init_state(), args.rounds))
    if not np.array_equal(est_b, est_f):
        print("plan_smoke: fused round is NOT bit-exact vs the banded "
              f"executor (max delta {np.abs(est_b - est_f).max()})",
              file=sys.stderr)
        return 1
    print(json.dumps({"auto": eng.plan_report(),
                      "bit_parity": True, "fused_bit_parity": True}),
          file=sys.stderr)

    # 4. plan manifest + doctor verdict
    manifest = os.path.join(args.outdir, "plan_ba.json")
    rc = cli_main(["plan", "--backend", "cpu",
                   "--generator", args.generator,
                   "--fire-policy", "every_round",
                   "--plan-backend", "tpu", "--explain",
                   "--report", manifest])
    if rc != 0:
        print(f"plan_smoke: plan CLI failed (rc={rc})", file=sys.stderr)
        return rc or 1
    return cli_main(["doctor", manifest])


if __name__ == "__main__":
    sys.exit(main())
