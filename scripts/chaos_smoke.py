#!/usr/bin/env python
"""CI chaos smoke: the crash-safety acceptance scenario.

Runs the ISSUE-13 acceptance criteria end to end on the CPU proxy and
leaves the recovery manifest in ``--outdir`` (uploaded by the tier1
workflow):

1. drive a 10k-node durability-armed SERVICE in a subprocess through a
   scripted churn stream (join/leave/update/edge events + compiled
   segments, drop>0) and SIGKILL it mid-run — between a ring archive's
   temp write and its atomic rename, the nastiest kill point;
2. recover from the durability directory (stale temp swept, newest
   valid ring checkpoint restored, WAL replayed) and resume the
   script: the final state must be BIT-EXACT (sha256 state digest) vs
   an uninterrupted control run;
3. the ``flow-updating-recovery-report/v1`` manifest must pass
   ``doctor --strict`` and ``inspect --blame`` must name the planted
   fault at rank 1;
4. the NEGATIVE control — the same fault with recovery disabled — must
   FAIL its signature (the conformance loop has both directions).

Exit code: 0 only if every assertion above holds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--nodes", type=int, default=10_000,
                    help="scripted-service member count (floor: 10k)")
    ap.add_argument("--ops", type=int, default=24,
                    help="scripted event-stream length")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.resilience.chaos import run_chaos

    fault = "kill_mid_checkpoint"
    t0 = time.perf_counter()
    out = run_chaos(fault, nodes=args.nodes, lanes=4,
                    segment_rounds=8, n_ops=args.ops, seed=args.seed,
                    outdir=args.outdir)
    print(f"chaos_smoke: {fault} at {args.nodes} nodes — "
          f"overall={out['overall']}, blame_top={out['blame_top']}, "
          f"recover={out['timings'].get('recover_s', '?')}s, "
          f"{time.perf_counter() - t0:.1f}s total", file=sys.stderr)
    if not (out["verify"] or {}).get("exact"):
        print(f"chaos_smoke: recovered state NOT bit-exact vs the "
              f"uninterrupted control: {out['verify']}",
              file=sys.stderr)
        return 1
    if out["blame_top"] != fault:
        print(f"chaos_smoke: blame ranked {out['blame_top']!r} first, "
              f"expected {fault!r}: {out['blame']}", file=sys.stderr)
        return 1

    # the negative control: recovery disabled, signature must FAIL
    bad = run_chaos(fault, nodes=max(256, args.nodes // 16), lanes=4,
                    segment_rounds=8, n_ops=args.ops, seed=args.seed,
                    outdir=args.outdir, perturb=True)
    if bad["exit_code"] == 0:
        print("chaos_smoke: the recovery-DISABLED control passed its "
              "signature — the gate cannot fail", file=sys.stderr)
        return 1
    print(f"chaos_smoke: negative control fails as declared "
          f"({[c['name'] for c in bad['checks'] if c['status'] == 'fail']})",
          file=sys.stderr)
    print(json.dumps({"fault": fault, "manifest":
                      out["manifest_path"],
                      "recover_s": out["timings"].get("recover_s")}))
    # doctor --strict over the saved manifest is the CI contract
    return cli_main(["doctor", "--strict", out["manifest_path"]])


if __name__ == "__main__":
    sys.exit(main())
