#!/usr/bin/env python
"""CI service smoke: the streaming engine's acceptance scenario.

Runs the ISSUE-7 acceptance criteria end to end on the CPU proxy and
leaves the manifest in ``--outdir`` (uploaded by the tier1 workflow):

1. build a ``ServiceEngine`` at capacity >= 100k nodes;
2. drive >= 100 scripted join/leave/update/edge events interleaved with
   compiled scan segments — asserting the round program compiles
   EXACTLY once across the whole run (zero recompiles);
3. mid-run, checkpoint -> restore -> continue BOTH services and assert
   the trajectories stay bit-exact on every state leaf;
4. write the ``flow-updating-service-report/v1`` manifest and run
   ``doctor`` over it — per-feature mass conserved at every membership
   epoch, post-churn residual decays, capacity accounting consistent.

Exit code: the doctor's (0 healthy; 1 on any failing check), or 1 on
any assertion above.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--nodes", type=int, default=99_000,
                    help="initial members (ring:N:2)")
    ap.add_argument("--capacity", type=int, default=100_000,
                    help="node-slot capacity (acceptance floor: 100k)")
    ap.add_argument("--events", type=int, default=120,
                    help="membership/edge events to apply (floor: 100)")
    ap.add_argument("--segment-rounds", type=int, default=8)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.models.rounds import run_rounds
    from flow_updating_tpu.obs.report import (
        build_service_manifest,
        write_report,
    )
    from flow_updating_tpu.service import ServiceEngine
    from flow_updating_tpu.topology.generators import ring

    t0 = time.perf_counter()
    topo = ring(args.nodes, k=2, seed=0)
    svc = ServiceEngine(topo, args.capacity, degree_budget=6,
                        segment_rounds=args.segment_rounds, seed=0)
    print(f"service_smoke: capacity {svc.capacity} nodes / "
          f"{svc.edge_capacity} edge slots, {svc.live_count} members, "
          f"built in {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    import tempfile

    cache0 = run_rounds._cache_size()
    rng = np.random.default_rng(0)
    held: list = []
    events = 0
    ckpt_done = False
    # checkpoint lives in a scratch dir, not the uploaded artifacts
    # (a 100k-capacity archive is tens of MB of CI noise)
    scratch = tempfile.mkdtemp(prefix="service-smoke-")
    path = os.path.join(scratch, "service_smoke.npz")
    while events < args.events:
        if held and (len(held) >= 16 or rng.random() < 0.4):
            svc.leave([held.pop()])
            events += 1
        else:
            slot = svc.join(float(rng.random()))
            a = int(rng.integers(0, args.nodes))
            svc.add_edges([(slot, a)])
            svc.update([a], [float(rng.random())])
            held.append(slot)
            events += 3
        svc.run(args.segment_rounds)
        if events >= args.events // 2 and not ckpt_done:
            # mid-churn durability: checkpoint -> restore -> both
            # continue -> bit-exact
            svc.save_checkpoint(path)
            twin = ServiceEngine.restore_checkpoint(path)
            svc.run(2 * args.segment_rounds)
            twin.run(2 * args.segment_rounds)
            for name in svc.state.__dataclass_fields__:
                a_, b_ = (np.asarray(getattr(svc.state, name)),
                          np.asarray(getattr(twin.state, name)))
                if not np.array_equal(a_, b_):
                    print(f"service_smoke: leaf {name} diverged after "
                          "checkpoint restore", file=sys.stderr)
                    return 1
            ckpt_done = True
            print("service_smoke: checkpoint -> restore -> continue is "
                  "bit-exact", file=sys.stderr)
    # quiet tail: the self-healing SLO needs the last churned epoch to
    # have recovered
    svc.run(8 * args.segment_rounds)

    compiles = run_rounds._cache_size() - cache0
    if compiles != 1:
        print(f"service_smoke: round program compiled {compiles}x over "
              f"{events} events (expected exactly 1)", file=sys.stderr)
        return 1
    print(f"service_smoke: {events} events, {svc.clock} rounds, "
          f"1 compile, live={svc.live_count}, "
          f"|residual|={float(np.max(np.abs(svc.mass_residual()))):.3e}, "
          f"{time.perf_counter() - t0:.1f}s total", file=sys.stderr)

    manifest_path = os.path.join(args.outdir, "service_report.json")
    write_report(manifest_path, build_service_manifest(
        argv=sys.argv[1:], config=svc.config, topo=topo,
        service=svc.service_block(), series=svc.boundary_series(),
        report=svc.convergence_report()))
    return cli_main(["doctor", manifest_path])


if __name__ == "__main__":
    sys.exit(main())
