#!/usr/bin/env python
"""CI scenario-conformance smoke: the adversarial loop end to end.

Runs two registered scenarios — one Byzantine (``byzantine_lie``: a
value-lying node poisons the average, blame must name it) and the
correlated-failure case (``partition_heal``: a community's bridges die
and heal, conservation must recover) — through the ``scenarios`` CLI:
seed grid under the sweep engine, representative field run, blame, and
the declared-signature conformance checks, writing the
``flow-updating-scenario-report/v1`` manifest into ``--outdir`` (the
tier1 workflow uploads it).

Then the negative control: the SAME Byzantine scenario with the planted
adversary removed must FAIL its signature (exit 1 from the CLI) — a
conformance suite that cannot reject the honest run asserts nothing.

Finally ``doctor --strict`` re-judges the saved manifest offline and
``inspect --blame`` must name the planted liar at rank 1 from the
manifest's field block alone.

Exit code: 0 when every step lands as declared; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCENARIOS = ["byzantine_lie", "partition_heal"]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--seeds", type=int, default=2)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    from flow_updating_tpu.cli import main as cli_main

    manifest_path = os.path.join(args.outdir, "scenario_report.json")
    rc = cli_main(["scenarios", *SCENARIOS, "--backend", "cpu",
                   "--seeds", str(args.seeds),
                   "--report", manifest_path, "--strict"])
    if rc != 0:
        print(f"scenario_smoke: conformance run failed (rc={rc})",
              file=sys.stderr)
        return rc or 1

    # negative control: the signature must REJECT the adversary-free run
    rc = cli_main(["scenarios", "byzantine_lie", "--backend", "cpu",
                   "--seeds", "1", "--perturb", "remove_adversary"])
    if rc == 0:
        print("scenario_smoke: PERTURBED run passed its signature — "
              "the conformance suite is vacuous", file=sys.stderr)
        return 1

    # doctor re-judges the saved manifest offline (the CI contract)
    rc = cli_main(["doctor", manifest_path, "--strict"])
    if rc != 0:
        print(f"scenario_smoke: doctor rejects the saved manifest "
              f"(rc={rc})", file=sys.stderr)
        return rc or 1

    # blame the planted liar from the manifest's own records
    with open(manifest_path) as f:
        manifest = json.load(f)
    by_name = {r["name"]: r for r in manifest["scenarios"]}
    liar = by_name["byzantine_lie"]["blame"]["liar"]
    planted = by_name["byzantine_lie"]["ground_truth"]["lie"]["nodes"]
    if not liar or liar[0]["node"] != planted[0]:
        print(f"scenario_smoke: blame ranked {liar[:1]}, expected "
              f"planted node {planted[0]} at rank 1", file=sys.stderr)
        return 1
    block = by_name["partition_heal"]["blame"].get("partition") or {}
    want = by_name["partition_heal"]["ground_truth"]["partition_block"]
    if block.get("block") != want:
        print(f"scenario_smoke: partition blame {block} != planted "
              f"block {want}", file=sys.stderr)
        return 1

    print(json.dumps({
        "scenario_smoke": "ok",
        "manifest": manifest_path,
        "scenarios": SCENARIOS,
        "blamed_liar": liar[0]["node"],
        "blamed_block": block.get("block"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
