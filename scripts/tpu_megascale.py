#!/usr/bin/env python
"""Mega-scale ladder: structured-stencil rounds/sec on virtual fat-trees.

The structured SpMV (`ops/structured.py`) needs no edge arrays, so the
node-count axis is bounded only by ~8 N-sized HBM vectors (+ host build
of the value/degree arrays).  This ladder measures gossip rounds/sec at
1M -> 66M nodes on ONE chip — the scaling-axis demonstration SURVEY.md
§5 asks for (node count 6 -> 1M and beyond), far past what the edge-array
paths can hold.

Writes its artifact (default MEGASCALE_TPU_r5.json, see --out)
progressively (one row per scale, banked as soon as measured) so a
mid-ladder tunnel wedge keeps earlier rows.  Each
row: nodes, rounds/s via the R-vs-2R scan difference (bench.measure_tpu,
launch-capped), fp32 state bytes, and a chunked convergence check
(rmse after 3x diameter-ish rounds).

Usage: python scripts/tpu_megascale.py [--ks 160,224,320,448,640]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "MEGASCALE_TPU_r5.json")


def measure_one(k: int) -> dict:
    import jax

    from bench import measure_tpu
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.models.sync import NodeKernel
    from flow_updating_tpu.topology.generators import fat_tree
    from flow_updating_tpu.utils.metrics import rmse

    t0 = time.time()
    topo = fat_tree(k, seed=0, materialize_edges=False)
    build_s = time.time() - t0
    row = {
        "k": k,
        "nodes": topo.num_nodes,
        "undirected_edges_virtual": 3 * k ** 3 // 4,
        "host_build_s": round(build_s, 2),
        "state_mb_fp32": round(topo.num_nodes * 4 * 8 / 1e6, 1),
        "platform": jax.devices()[0].platform,
    }
    m = measure_tpu(topo, 64, kernel="node", spmv="structured")
    row.update({kk: m[kk] for kk in (
        "rounds_per_sec", "per_round_s", "plan_s", "compile_s", "rounds")})

    # convergence: run chunks until rmse < 1e-6 or the round budget ends
    # (fat-tree diameter is 6; mixing needs a few hundred rounds at any k)
    cfg = RoundConfig.fast(variant="collectall", kernel="node",
                           spmv="structured")
    kern = NodeKernel(topo, cfg)
    st = kern.init_state()
    budget, chunk, used = 4096, 256, 0
    err = None
    while used < budget:
        st = kern.run(st, chunk)
        used += chunk
        err = float(rmse(kern.estimates(st), topo.true_mean))
        if err < 1e-6:
            break
    row["rounds_to_rmse"] = {"rounds": used, "rmse": err,
                             "converged": err is not None and err < 1e-6}
    return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ks", default="160,224,320,448,640")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="artifact path (progressively banked + merged)")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="permit banking non-TPU rows (testing only; the "
                         "artifact is the round's TPU number of record)")
    args = ap.parse_args()
    out_path = args.out

    import jax

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon") and not args.allow_cpu:
        print(json.dumps({"error": f"backend is {platform!r}, not TPU — "
                          "refusing to bank CPU rows into the TPU "
                          "artifact (use --allow-cpu for wiring tests)"}))
        return 2

    banked = {"what": "structured-stencil ladder on virtual fat-trees, "
                      "one chip", "rows": []}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                prior = json.load(f)
            if isinstance(prior, dict) and prior.get("rows"):
                banked = prior
        except (OSError, json.JSONDecodeError):
            pass
    have = {r.get("k") for r in banked["rows"] if "rounds_per_sec" in r}

    for ks in args.ks.split(","):
        k = int(ks)
        if k in have:
            print(f"k={k}: already banked, skipping", flush=True)
            continue
        try:
            row = measure_one(k)
        except Exception as exc:  # bank the failure, stop the ladder
            row = {"k": k, "error": f"{type(exc).__name__}: {exc}"[:400]}
            banked["rows"] = [r for r in banked["rows"] if r.get("k") != k]
            banked["rows"].append(row)
            with open(out_path, "w") as f:
                json.dump(banked, f, indent=1)
            print(json.dumps(row), flush=True)
            return 1
        banked["rows"] = [r for r in banked["rows"] if r.get("k") != k]
        banked["rows"].append(row)
        banked["rows"].sort(key=lambda r: r["k"])
        with open(out_path, "w") as f:
            json.dump(banked, f, indent=1)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
