#!/usr/bin/env python
"""TPU scale-ladder diagnostic (VERDICT r2 item 1).

Runs the fast collect-all kernels on the ambient backend up a fat-tree
scale ladder (k=8 -> 160), each scale in its OWN subprocess so a TPU
worker crash is isolated and its full stderr is captured.  Per step it
logs wall times for bounded scan lengths plus `device.memory_stats()`.

Usage:
    python scripts/tpu_ladder.py                   # full ladder, node kernel
    python scripts/tpu_ladder.py --ks 8 40 --kernel edge
    python scripts/tpu_ladder.py --child --k 160 ...   (internal)

Writes a JSON report to TPU_LADDER.json (repo root) unless --no-report.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def child(args) -> None:
    import jax

    from bench import make_runner
    from flow_updating_tpu.topology.generators import fat_tree

    dev = jax.devices()[0]
    out = {"k": args.k, "kernel": args.kernel, "spmv": args.spmv,
           "device": str(dev), "platform": dev.platform}

    t0 = time.perf_counter()
    topo = fat_tree(args.k, seed=0)
    out["build_s"] = round(time.perf_counter() - t0, 3)
    out["nodes"] = topo.num_nodes
    out["edges"] = topo.num_edges

    # same measurement closure as the headline bench (bench.make_runner),
    # so ladder timings and bench timings are directly comparable
    run, read = make_runner(topo, kernel=args.kernel, spmv=args.spmv)

    def mem():
        try:
            s = dev.memory_stats()
            return {k: s[k] for k in ("bytes_in_use", "peak_bytes_in_use")
                    if k in s}
        except Exception as e:  # platform may not expose stats
            return {"err": str(e)[:120]}

    out["mem_after_build"] = mem()
    steps = []
    last = None
    for r in args.round_ladder:
        t0 = time.perf_counter()
        last = run(r)
        wall = time.perf_counter() - t0
        # second run of the same length: compile cached -> pure exec+launch
        t0 = time.perf_counter()
        last = run(r)
        exec_s = time.perf_counter() - t0
        steps.append({"rounds": r, "first_s": round(wall, 4),
                      "exec_s": round(exec_s, 4), "mem": mem()})
        print(f"  k={args.k} rounds={r}: first={wall:.3f}s exec={exec_s:.3f}s",
              file=sys.stderr, flush=True)
    out["steps"] = steps
    if steps:
        r_a, r_b = args.round_ladder[-2:] if len(args.round_ladder) > 1 else (
            0, args.round_ladder[-1])
        ea = next(s["exec_s"] for s in steps if s["rounds"] == r_a) \
            if r_a else 0.0
        eb = steps[-1]["exec_s"]
        if r_b > r_a:
            out["per_round_s"] = round((eb - ea) / (r_b - r_a), 6)
            out["rounds_per_sec"] = round(1.0 / max(out["per_round_s"], 1e-9), 2)
    from flow_updating_tpu.utils.metrics import rmse

    out["rmse_after"] = float(rmse(read(last), topo.true_mean))
    print(json.dumps(out))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--ks", type=int, nargs="+", default=[8, 40, 96, 160])
    ap.add_argument("--kernel", default="node", choices=("node", "edge"))
    ap.add_argument("--spmv", default="xla", choices=("xla", "pallas"))
    ap.add_argument("--round-ladder", type=int, nargs="+",
                    default=[64, 256, 1024, 4096])
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--no-report", action="store_true")
    args = ap.parse_args()

    if args.child:
        child(args)
        return

    report = {"ladder": [], "argv": sys.argv[1:]}
    for k in args.ks:
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--k", str(k), "--kernel", args.kernel, "--spmv", args.spmv,
               "--round-ladder", *map(str, args.round_ladder)]
        print(f"=== ladder k={k} ({args.kernel}/{args.spmv}) ===",
              file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        try:
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=args.timeout, cwd=REPO)
            entry = {"k": k, "rc": p.returncode,
                     "wall_s": round(time.perf_counter() - t0, 1)}
            line = (p.stdout.strip().splitlines() or [""])[-1]
            try:
                entry["result"] = json.loads(line)
            except json.JSONDecodeError:
                entry["stdout_tail"] = p.stdout[-1000:]
            if p.returncode != 0:
                entry["stderr_tail"] = p.stderr[-3000:]
            else:
                entry["stderr_tail"] = p.stderr[-500:]
        except subprocess.TimeoutExpired as e:
            entry = {"k": k, "rc": "timeout",
                     "wall_s": round(time.perf_counter() - t0, 1),
                     "stderr_tail": ((e.stderr or b"").decode("utf-8", "replace")
                                     if isinstance(e.stderr, bytes)
                                     else (e.stderr or ""))[-3000:]}
        report["ladder"].append(entry)
        ok = entry["rc"] == 0
        print(f"=== k={k}: rc={entry['rc']} wall={entry['wall_s']}s "
              f"{'OK' if ok else 'FAILED'} ===", file=sys.stderr, flush=True)
        if not ok:
            break  # higher scales will only be worse; keep the tunnel alive

    if not args.no_report:
        with open(os.path.join(REPO, "TPU_LADDER.json"), "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps(report["ladder"], indent=1)[:4000])


if __name__ == "__main__":
    main()
