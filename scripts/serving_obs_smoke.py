#!/usr/bin/env python
"""CI serving-observability smoke: the flight recorder's acceptance
scenario on the CPU proxy (ISSUE 17; docs/OBSERVABILITY.md §8).

1. drive a 10k-node ``QueryFabric`` (flight recorder on, latency SLOs
   declared) through >= 32 cohort queries under membership churn;
   every terminated query must leave a GAP-FREE span chain and the
   streaming counters must match the census exactly;
2. write the ``flow-updating-query-report/v1`` manifest with its
   embedded ``flow-updating-serving-trace/v1`` block plus the
   Prometheus text export, and pass ``doctor --strict`` over it
   (slo_latency / span_complete / metrics_consistency included);
3. render the manifest as a Perfetto trace (``obs export-trace``
   path) — per-lane tracks + counter samples must come out non-empty;
4. SIGKILL a mid-flight fabric for real (the chaos harness's
   subprocess kill) and recover: the conformance gate — which now
   includes the serving-trace checks — must pass, and the trace must
   carry the explicit ``recovery`` span;
5. the NEGATIVE control — same fault, replay disabled — must FAIL
   ``span_complete`` specifically: the black box can tell a real
   recovery from a lobotomized one.

Exit code: 0 only if every assertion above holds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--nodes", type=int, default=10_000,
                    help="fabric member count (acceptance floor: 10k)")
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--queries", type=int, default=36,
                    help="queries to offer (acceptance floor: 32)")
    ap.add_argument("--events", type=int, default=16,
                    help="membership churn events between segments")
    ap.add_argument("--segment-rounds", type=int, default=8)
    ap.add_argument("--eps", type=float, default=1e-2)
    ap.add_argument("--max-rounds", type=int, default=4096)
    ap.add_argument("--chaos-ops", type=int, default=20,
                    help="scripted ops for the SIGKILL leg")
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.obs import health
    from flow_updating_tpu.obs.report import (
        build_query_manifest,
        write_report,
    )
    from flow_updating_tpu.query import QueryFabric
    from flow_updating_tpu.resilience.chaos import run_chaos
    from flow_updating_tpu.topology.generators import erdos_renyi

    # -- 1: the churn run with the recorder on ----------------------------
    t0 = time.perf_counter()
    topo = erdos_renyi(args.nodes, avg_degree=6.0, seed=0)
    fab = QueryFabric(topo, lanes=args.lanes, capacity=args.nodes + 64,
                      degree_budget=24,
                      segment_rounds=args.segment_rounds, seed=0,
                      conv_eps=args.eps,
                      admission_slo_rounds=64 * args.segment_rounds,
                      convergence_slo_rounds=64 * args.segment_rounds)
    rng = np.random.default_rng(0)
    members = fab.svc.live_ids()
    held: list = []
    submitted = events = rounds = 0
    while (submitted < args.queries or fab.active_lanes or fab.queued) \
            and rounds < args.max_rounds:
        arrivals = min(int(rng.poisson(0.5 * args.lanes)),
                       args.queries - submitted)
        for _ in range(arrivals):
            m = int(rng.integers(8, 64))
            cohort = rng.choice(members, size=m, replace=False)
            fab.submit(rng.random(m), cohort=np.sort(cohort))
            submitted += 1
        if events < args.events:
            if held and rng.random() < 0.4:
                fab.leave([held.pop()])
            else:
                slot = fab.join()
                fab.add_edges([(slot, int(rng.integers(0, args.nodes)))])
                held.append(slot)
            events += 1
        fab.run(args.segment_rounds)
        rounds += args.segment_rounds
    if fab.retired_total < args.queries:
        print(f"serving_obs_smoke: only {fab.retired_total}/"
              f"{args.queries} queries retired in {rounds} rounds",
              file=sys.stderr)
        return 1

    # every terminated chain gap-free, counters exact — asserted here
    # AND re-judged by doctor below (belt and braces)
    chains = fab.spans.block()["queries"]
    for qid, chain in chains.items():
        terms = [c for c in chain
                 if c["name"] in ("retired", "quarantined")]
        gap = health._span_chain_gap(chain, terms[0]["t0"]) \
            if terms else "never terminated"
        if gap is not None:
            print(f"serving_obs_smoke: qid {qid} chain not gap-free: "
                  f"{gap}", file=sys.stderr)
            return 1
    if fab.metrics.counter("queries_retired_total") != fab.retired_total:
        print("serving_obs_smoke: retired counter disagrees with the "
              "fabric census", file=sys.stderr)
        return 1
    print(f"serving_obs_smoke: {submitted} queries / {args.lanes} lanes "
          f"at {args.nodes} nodes, {events} churn events, {rounds} "
          f"rounds, {len(chains)} gap-free chains, "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # -- 2: manifest + Prometheus + doctor --strict -----------------------
    manifest_path = os.path.join(args.outdir, "serving_obs_report.json")
    write_report(manifest_path, build_query_manifest(
        argv=sys.argv[1:], config=fab.svc.config, topo=topo,
        query=fab.query_block(),
        extra={"serving_trace": fab.serving_trace_block()}))
    with open(os.path.join(args.outdir, "serving_obs_metrics.prom"),
              "w") as f:
        f.write(fab.metrics.to_prometheus())
    rc = cli_main(["doctor", manifest_path, "--strict"])
    if rc != 0:
        print("serving_obs_smoke: doctor --strict FAILED on the "
              "serving-trace manifest", file=sys.stderr)
        return 1

    # -- 3: the Perfetto rendering ----------------------------------------
    trace_path = os.path.join(args.outdir, "serving_obs.trace.json")
    rc = cli_main(["obs", "export-trace", manifest_path,
                   "--output", trace_path])
    if rc != 0:
        return 1
    with open(trace_path) as f:
        doc = json.load(f)
    slices = [e for e in doc["traceEvents"]
              if e.get("ph") == "X" and e.get("cat") == "query"]
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    if len(slices) < args.queries or not counters:
        print(f"serving_obs_smoke: trace rendered {len(slices)} query "
              f"slices / {len(counters)} counter events (expected "
              f">= {args.queries} and > 0)", file=sys.stderr)
        return 1

    # -- 4: the real SIGKILL ------------------------------------------------
    t1 = time.perf_counter()
    out = run_chaos("kill_at_segment", nodes=args.nodes,
                    lanes=args.lanes, segment_rounds=args.segment_rounds,
                    n_ops=args.chaos_ops, seed=0, outdir=args.outdir)
    by = {c["name"]: c["status"] for c in out["checks"]}
    print(f"serving_obs_smoke: SIGKILL leg overall={out['overall']} "
          f"({time.perf_counter() - t1:.1f}s) checks={by}",
          file=sys.stderr)
    if out["exit_code"] != 0 or by.get("span_complete") != "pass" \
            or by.get("metrics_consistency") != "pass":
        print("serving_obs_smoke: the recovered fabric's trace did not "
              "pass the serving checks", file=sys.stderr)
        return 1
    with open(out["manifest_path"]) as f:
        m = json.load(f)
    rspans = [s for s in m["serving_trace"]["spans"]["engine"]
              if s["name"] == "recovery"]
    if not rspans or not rspans[-1]["replay_enabled"]:
        print("serving_obs_smoke: no replay-enabled recovery span in "
              "the recovered trace", file=sys.stderr)
        return 1

    # -- 5: the negative control ------------------------------------------
    bad = run_chaos("kill_at_segment", nodes=max(256, args.nodes // 16),
                    lanes=args.lanes, segment_rounds=args.segment_rounds,
                    n_ops=args.chaos_ops, seed=0, outdir=args.outdir,
                    perturb=True)
    bad_by = {c["name"]: c["status"] for c in bad["checks"]}
    if bad["exit_code"] == 0 or bad_by.get("span_complete") != "fail":
        print(f"serving_obs_smoke: replay-DISABLED control did not fail "
              f"span_complete: exit={bad['exit_code']} checks={bad_by}",
              file=sys.stderr)
        return 1
    print("serving_obs_smoke: negative control failed span_complete as "
          "designed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
