#!/usr/bin/env python
"""Multi-chip scaling curves on a virtual CPU mesh (VERDICT r3 item 5).

For S in {1, 2, 4, 8} this records, per distributed execution path and
topology: rounds/s (R-vs-2R scan difference — launch overhead cancels)
and the program's collective traffic. Two independent byte numbers are
reported:

* ``hlo_collective_bytes``: parsed from the XLA-optimized HLO of the
  compiled round program — every all-gather / all-reduce /
  collective-permute / reduce-scatter / all-to-all op's output bytes.
  This is what the compiler actually scheduled (GSPMD paths have no
  hand-written collectives to introspect; SURVEY §2c-2).
* ``planned_bytes`` (halo paths only): the shard plan's own accounting
  (`ShardPlan.collective_bytes_per_round`).

CPU-mesh wall-clock is NOT a TPU perf prediction — the value of the
curve is the *shape* (how rounds/s and bytes move with S) and that the
sharded programs execute correctly at every S. The driver-level
correctness gate is `__graft_entry__.dryrun_multichip`.

Each S needs its own interpreter (`xla_force_host_platform_device_count`
is fixed at backend init), so the parent re-execs per S with the proven
CPU-pinned env (`flow_updating_tpu.utils.backend.cpu_subprocess_env`).

Output: MULTICHIP_SCALING_r4.json at the repo root.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4,
    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}
_COLLECTIVES = ("all-gather", "all-reduce", "collective-permute",
                "reduce-scatter", "all-to-all")
# `f32[8,522]{1,0} all-gather(...)`; tuple-shaped collectives list every
# element shape: `(f32[522]{0}, f32[522]{0}) all-reduce(...)`
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Sum output bytes of collective ops in optimized HLO, by op kind.

    A `lax.scan` body appears once in HLO but executes every round, so
    on a round-scan program this is per-round traffic (plus any one-time
    prologue collectives, which are negligible and included)."""
    per_kind: dict = {k: 0 for k in _COLLECTIVES}
    count = 0
    for line in hlo_text.splitlines():
        s = line.strip()
        # match ` = <shape> <kind>(`; skip -start/-done pairs' duplicates
        m = re.search(r"= (.+?) (" + "|".join(_COLLECTIVES) + r")\(", s)
        if not m or m.group(2) + "-done" in s:
            continue
        shapes, kind = m.group(1), m.group(2)
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(shapes):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        per_kind[kind] += nbytes
        count += 1
    return {"total": sum(per_kind.values()), "ops": count,
            **{k: v for k, v in per_kind.items() if v}}


def _time_scan(run, state, r: int):
    """Seconds/round via the R-vs-2R difference (overhead cancels).

    Returns ``(sec_per_round, noisy)``: the median of 5 difference
    measurements, growing R when the spread is noise-dominated (short
    CPU-mesh scans can time *negative* otherwise — seen on the S=4 halo
    path at R=8).  ``noisy=True`` marks a measurement that never met the
    spread gate (shared-host CPU load): the median is still the best
    available estimate, but the row must say so — and must never
    displace a clean banked row (see _merge_keep_best)."""
    import jax

    med = None
    for _ in range(3):
        jax.block_until_ready(run(state, r))      # compile + warm
        jax.block_until_ready(run(state, 2 * r))
        diffs = []
        for _rep in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(run(state, r))
            t1 = time.perf_counter()
            jax.block_until_ready(run(state, 2 * r))
            t2 = time.perf_counter()
            diffs.append(((t2 - t1) - (t1 - t0)) / r)
        diffs.sort()
        med = diffs[len(diffs) // 2]
        if med > 0 and diffs[1] > 0.25 * med:
            return med, False
        r *= 4
    if med is None or med <= 0:
        raise RuntimeError(f"timing unusable (last diffs {diffs})")
    print(f"WARNING: noisy timing, using median {med:.3g} s/round "
          f"(diffs {diffs})", file=sys.stderr, flush=True)
    return med, True


def _topologies():
    from flow_updating_tpu.topology.generators import erdos_renyi, fat_tree

    return {
        "fat_tree_k24": fat_tree(24),            # 4,176 nodes / 20,736 edges
        "er_16k": erdos_renyi(16384, avg_degree=8.0, seed=0),
    }


def child(n_devices: int) -> None:
    import jax

    assert len(jax.devices()) >= n_devices, (
        f"{len(jax.devices())} devices, need {n_devices}")

    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.models.state import init_state
    from flow_updating_tpu.models.rounds import node_estimates, run_rounds
    from flow_updating_tpu.parallel import sharded
    from flow_updating_tpu.parallel.mesh import make_mesh
    from flow_updating_tpu.parallel.spmv_sharded import ShardedNodeKernel
    import numpy as np

    S = n_devices
    mesh = make_mesh(S) if S > 1 else None
    results = []
    cfg = RoundConfig.fast(variant="collectall")

    for tname, topo in _topologies().items():
        # single-device reference estimates for correctness at this scale
        k1 = sync.NodeKernel(topo, cfg)
        ref_est = k1.estimates(k1.run(k1.init_state(), 8))

        # -- GSPMD node kernel ------------------------------------------
        kern = sync.NodeKernel(topo, cfg, mesh=mesh)
        st = kern.init_state()
        spr, noisy = _time_scan(kern.run, st, 64)
        hlo = (jax.jit(lambda s: kern.run(s, 64))
               .lower(st).compile().as_text())
        est = kern.estimates(kern.run(st, 8))
        np.testing.assert_allclose(est, ref_est, atol=1e-5)
        results.append({
            "path": "gspmd_node", "topology": tname, "shards": S,
            "rounds_per_sec": round(1.0 / spr, 2),
            "hlo_collective_bytes": hlo_collective_bytes(hlo),
            **({"noisy": True} if noisy else {}),
        })

        # -- GSPMD node kernel, structured stencil SpMV -----------------
        if topo.structure is not None:
            scfg = dataclasses.replace(cfg, spmv="structured")
            ks = sync.NodeKernel(topo, scfg, mesh=mesh)
            st = ks.init_state()
            spr, noisy = _time_scan(ks.run, st, 64)
            hlo = (jax.jit(lambda s: ks.run(s, 64))
                   .lower(st).compile().as_text())
            est = ks.estimates(ks.run(st, 8))
            np.testing.assert_allclose(est, ref_est, atol=1e-5)
            results.append({
                "path": "gspmd_structured", "topology": tname, "shards": S,
                "rounds_per_sec": round(1.0 / spr, 2),
                "hlo_collective_bytes": hlo_collective_bytes(hlo),
                **({"noisy": True} if noisy else {}),
            })

        # -- pod-sharded fat-tree stencil (shard_map, one k/2-element
        #    psum per round) ---------------------------------------------
        from flow_updating_tpu.ops.structured import FatTreeStruct
        from flow_updating_tpu.parallel.structured_sharded import (
            PodShardedFatTreeKernel,
        )

        if (mesh is not None and isinstance(topo.structure, FatTreeStruct)
                and topo.structure.k % S == 0):
            kp = PodShardedFatTreeKernel(
                topo, dataclasses.replace(cfg, spmv="structured"), mesh)
            st = kp.init_state()
            spr, noisy = _time_scan(kp.run, st, 64)
            hlo = (jax.jit(lambda s: kp.run(s, 64))
                   .lower(st).compile().as_text())
            est = kp.estimates(kp.run(st, 8))
            np.testing.assert_allclose(est, ref_est, atol=1e-5)
            results.append({
                "path": "pod_structured", "topology": tname, "shards": S,
                "rounds_per_sec": round(1.0 / spr, 2),
                "hlo_collective_bytes": hlo_collective_bytes(hlo),
                **({"noisy": True} if noisy else {}),
            })

        # -- sharded fused-circuit SpMV (shard_map) ---------------------
        if mesh is not None:
            kb = ShardedNodeKernel(
                topo, dataclasses.replace(cfg, spmv="benes_fused"), mesh)
            st = kb.init_state()
            spr, noisy = _time_scan(kb.run, st, 16)
            hlo = (jax.jit(lambda s: kb.run(s, 16))
                   .lower(st).compile().as_text())
            est = kb.estimates(kb.run(st, 8))
            np.testing.assert_allclose(est, ref_est, atol=1e-5)
            results.append({
                "path": "sharded_fused", "topology": tname, "shards": S,
                "rounds_per_sec": round(1.0 / spr, 2),
                "hlo_collective_bytes": hlo_collective_bytes(hlo),
                **({"noisy": True} if noisy else {}),
            })

        # -- shard_map halo kernel (edge state), both exchanges, both
        #    fast protocol modes (collect-all messages; pairwise's direct
        #    endpoint-estimate exchange) -------------------------------
        if mesh is not None:
            for pcfg, pname in (
                (cfg, ""),
                (RoundConfig.fast(variant="pairwise"), "_fastpair"),
            ):
                ref_state = init_state(topo, pcfg)
                ref_arrays = topo.device_arrays(
                    coloring=pcfg.needs_coloring)
                eref = np.asarray(node_estimates(
                    run_rounds(ref_state, ref_arrays, pcfg, 4),
                    ref_arrays))
                plan = sharded.plan_sharding(
                    topo, S, partition="bfs",
                    coloring=pcfg.needs_coloring)
                planned = plan.collective_bytes_per_round()
                for halo in ("ppermute", "allgather"):
                    st = sharded.init_plan_state(plan, pcfg, mesh)

                    def run(s, n, _h=halo, _c=pcfg, _p=plan):
                        return sharded.run_rounds_sharded(
                            s, _p, _c, mesh, n, halo=_h)

                    spr, noisy = _time_scan(run, st, 8)
                    hlo = (jax.jit(lambda s: run(s, 8))
                           .lower(st).compile().as_text())
                    est = sharded.gather_estimates(run(st, 4), plan)
                    np.testing.assert_allclose(est, eref, atol=1e-5)
                    results.append({
                        "path": f"halo_{halo}{pname}", "topology": tname,
                        "shards": S,
                        "rounds_per_sec": round(1.0 / spr, 2),
                        "hlo_collective_bytes": hlo_collective_bytes(hlo),
                        "planned_bytes": {
                            "per_round": planned[f"{halo}_bytes"],
                            "cut_fraction": planned["cut_fraction"],
                        },
                        **({"noisy": True} if noisy else {}),
                    })

    print("RESULTS " + json.dumps(results))


def child_mega(S: int, k: int) -> None:
    """Mega-scale pod-stencil evidence (VERDICT r4 item 8): the 66M-node
    design point's sharding, exercised at the largest scale a CPU mesh
    can hold — a VIRTUAL fat-tree (no edge arrays; k=344 is 10.3M nodes)
    at S shards.  Records rounds/s shape + HLO collective bytes and
    asserts estimate parity against the single-device structured kernel.
    CPU wall-clock is not a TPU prediction; the evidence is that the
    sharded program compiles, executes, matches, and moves O(k) bytes
    per round regardless of node count."""
    import numpy as np

    import jax

    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.ops.structured import FatTreeStruct
    from flow_updating_tpu.parallel.mesh import make_mesh
    from flow_updating_tpu.parallel.structured_sharded import (
        PodShardedFatTreeKernel,
    )
    from flow_updating_tpu.topology.generators import fat_tree

    assert len(jax.devices()) >= S, f"{len(jax.devices())} devices < {S}"
    mesh = make_mesh(S)
    cfg = RoundConfig.fast(variant="collectall", kernel="node",
                           spmv="structured")
    topo = fat_tree(k, seed=0, materialize_edges=False)
    assert isinstance(topo.structure, FatTreeStruct)
    assert topo.structure.k % S == 0, (k, S)
    tname = f"fat_tree_k{k}_virtual"
    results = []

    # single-device structured reference at the same scale
    k1 = sync.NodeKernel(topo, cfg)
    ref_est = k1.estimates(k1.run(k1.init_state(), 8))

    runs = [("pod_structured",
             PodShardedFatTreeKernel(topo, cfg, mesh)),
            ("gspmd_structured",
             sync.NodeKernel(topo, cfg, mesh=mesh))]
    for path, kern in runs:
        st = kern.init_state()
        spr, noisy = _time_scan(kern.run, st, 8)
        hlo = (jax.jit(lambda s, _k=kern: _k.run(s, 8))
               .lower(st).compile().as_text())
        est = kern.estimates(kern.run(st, 8))
        # fp32 at 10M+ nodes: sharded stencil reductions accumulate in a
        # different order than the single-device kernel; observed max
        # deviation 1.5e-5 on values ~0.5 (0.27% of elements past 1e-5).
        # 5e-5 still catches any semantic error by orders of magnitude.
        np.testing.assert_allclose(est, ref_est, atol=5e-5)
        results.append({
            "path": path, "topology": tname, "shards": S,
            "nodes": topo.num_nodes,
            "rounds_per_sec": round(1.0 / spr, 2),
            "hlo_collective_bytes": hlo_collective_bytes(hlo),
            **({"noisy": True} if noisy else {}),
        })

    print("RESULTS " + json.dumps(results))


def _merge_keep_best(out_path: str, fresh: list) -> list:
    """Merge fresh rows into a banked artifact, keeping the best
    measurement per (path, topology, shards).

    Same code on the same harness: a slower wall-clock is contention
    noise, so higher rounds/s is the better measurement — and a clean
    (non-noisy) row always beats a noisy one (numbers-of-record
    convention; a degraded re-run must never clobber a good banked
    row)."""
    banked = {}
    try:
        with open(out_path) as f:
            for r in json.load(f).get("results", []):
                banked[(r["path"], r["topology"], r["shards"])] = r
    except (OSError, json.JSONDecodeError, KeyError, TypeError):
        pass
    for r in fresh:
        key = (r["path"], r["topology"], r["shards"])
        old = banked.get(key)
        if old is None:
            banked[key] = r
            continue
        old_clean = not old.get("noisy")
        new_clean = not r.get("noisy")
        if (new_clean, r["rounds_per_sec"]) >= (
                old_clean, old["rounds_per_sec"]):
            banked[key] = r
    return sorted(banked.values(),
                  key=lambda r: (r["topology"], r["path"], r["shards"]))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", type=int, default=0)
    ap.add_argument("--shards", default="1,2,4,8")
    ap.add_argument("--mega-k", type=int, default=0,
                    help="also run the mega-scale virtual fat-tree "
                         "section (pod/gspmd structured only) at this "
                         "arity on the LARGEST shard count (e.g. 344 = "
                         "10.3M nodes at S=8)")
    ap.add_argument("--mega-only", action="store_true",
                    help="skip the standard S-ladder; run only --mega-k")
    ap.add_argument("--out", default=os.path.join(
        REPO, "MULTICHIP_SCALING_r5.json"))
    args = ap.parse_args(argv)

    if args.child:
        if args.mega_k:
            child_mega(args.child, args.mega_k)
        else:
            child(args.child)
        return 0

    sys.path.insert(0, REPO)
    from flow_updating_tpu.utils.backend import cpu_subprocess_env

    shard_list = [int(s) for s in args.shards.split(",")]
    jobs = [] if args.mega_only else [(S, []) for S in shard_list]
    if args.mega_k:
        jobs.append((max(shard_list), ["--mega-k", str(args.mega_k)]))

    all_results = []
    for S, extra in jobs:
        env = cpu_subprocess_env(n_virtual_devices=max(S, 2),
                                 extra_path=REPO)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(S),
             *extra],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=3600)
        if proc.returncode != 0:
            print(proc.stdout[-2000:], file=sys.stderr)
            print(proc.stderr[-4000:], file=sys.stderr)
            raise RuntimeError(f"child S={S} failed rc={proc.returncode}")
        # surface noisy-timing warnings even on success — a degraded
        # measurement must be visible to the operator, not just flagged
        # in the JSON row
        for wline in proc.stderr.splitlines():
            if "WARNING" in wline:
                print(f"S={S} {wline}", file=sys.stderr, flush=True)
        for line in proc.stdout.splitlines():
            if line.startswith("RESULTS "):
                all_results.extend(json.loads(line[len("RESULTS "):]))
        print(f"S={S}: done ({len(all_results)} rows total)")

    all_results = _merge_keep_best(args.out, all_results)
    out = {
        "meta": {
            "harness": "virtual CPU mesh (xla_force_host_platform_device_"
                       "count); wall-clock is curve-shape evidence, not a "
                       "TPU prediction — see scripts/multichip_scaling.py",
            "timing": "R-vs-2R scan difference (median of 5; rows with "
                      "'noisy': true never met the spread gate and never "
                      "displace a banked clean row)",
            "correctness": "every row's estimates checked against the "
                           "single-device kernel (atol 1e-5)",
        },
        "results": all_results,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    # human-readable table
    print(f"\n{'path':<16}{'topology':<14}{'S':>3}{'rounds/s':>12}"
          f"{'hlo coll. B':>14}")
    for r in all_results:
        print(f"{r['path']:<16}{r['topology']:<14}{r['shards']:>3}"
              f"{r['rounds_per_sec']:>12}"
              f"{r['hlo_collective_bytes']['total']:>14}")
    print(f"\nwrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
