#!/usr/bin/env python
"""Multi-chip scaling curves on a virtual CPU mesh (VERDICT r3 item 5;
weak-scaling ladder + overlap schedule added in PR 8).

Two ladder shapes share the harness:

* the **standard (strong) ladder** — fixed topologies, S in {1,2,4,8}:
  per distributed execution path, rounds/s (R-vs-2R scan difference —
  launch overhead cancels) and the program's collective traffic;
* the **weak-scaling ladder** (``--weak N``) — fixed nodes PER SHARD:
  an Erdős–Rényi graph of ``N*S`` nodes per S, so the ideal curve is a
  FLAT rounds/s line and ``per_chip_efficiency = rate_S / rate_1`` is
  written onto every multi-shard row.  Halo rows cover all three
  exchange schedules (``ppermute`` / ``allgather`` / ``overlap``), and
  overlap rows also record ``overlap_ratio`` — the fraction of the
  exchange hidden behind interior compute, from the same timing
  harness via the interior-elided probe program.

Two independent byte numbers are reported:

* ``hlo_collective_bytes``: parsed from the XLA-optimized HLO of the
  compiled round program (``obs.profile.hlo_collective_bytes``) —
  per-round, per-shard bytes the compiler actually scheduled;
* ``planned_bytes`` (halo paths only): the shard plan's own accounting
  (`ShardPlan.collective_bytes_per_round`); the two are pinned against
  each other in ``tests/test_parallel.py``.

CPU-mesh wall-clock is NOT a TPU perf prediction — the value of the
curve is the *shape* (how rounds/s and bytes move with S) and that the
sharded programs execute correctly at every S. The driver-level
correctness gate is `__graft_entry__.dryrun_multichip`; the per-chip
efficiency rows are gated in CI by ``regress`` against the banked
``MULTICHIP_SCALING_*`` history (``--smoke`` is the CI entry: a
2-shard weak ladder with the overlap-vs-ppermute bit-parity asserted
in-child).

Each S needs its own interpreter (`xla_force_host_platform_device_count`
is fixed at backend init), so the parent re-execs per S with the proven
CPU-pinned env (`flow_updating_tpu.utils.backend.cpu_subprocess_env`).

Output: MULTICHIP_SCALING_r6.json at the repo root.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Shared implementation lives with the other program-cost tooling
    in :mod:`flow_updating_tpu.obs.profile` (import deferred: the
    parent process never initializes jax)."""
    from flow_updating_tpu.obs.profile import hlo_collective_bytes as f

    return f(hlo_text)


def _time_scan(run, state, r: int):
    """Seconds/round via the R-vs-2R difference (overhead cancels).

    Returns ``(sec_per_round, noisy, timing)``: the median of 5
    difference measurements, growing R when the spread is
    noise-dominated (short CPU-mesh scans can time *negative* otherwise
    — seen on the S=4 halo path at R=8).  ``noisy=True`` marks a
    measurement that never met the spread gate (shared-host CPU load):
    the median is still the best available estimate, but the row must
    say so — and must never displace a clean banked row (see
    _merge_keep_best).  ``timing`` records what was ACTUALLY measured
    (final round count, repeats, max-min spread in bench.py's
    convention) so downstream baseline banking never has to invent
    quality metadata."""
    import jax

    med = None
    for _ in range(3):
        jax.block_until_ready(run(state, r))      # compile + warm
        jax.block_until_ready(run(state, 2 * r))
        diffs = []
        for _rep in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(run(state, r))
            t1 = time.perf_counter()
            jax.block_until_ready(run(state, 2 * r))
            t2 = time.perf_counter()
            diffs.append(((t2 - t1) - (t1 - t0)) / r)
        diffs.sort()
        med = diffs[len(diffs) // 2]
        if med > 0 and diffs[1] > 0.25 * med:
            return med, False, _timing_info(r, diffs, med)
        r *= 4
    if med is None or med <= 0:
        raise RuntimeError(f"timing unusable (last diffs {diffs})")
    print(f"WARNING: noisy timing, using median {med:.3g} s/round "
          f"(diffs {diffs})", file=sys.stderr, flush=True)
    return med, True, _timing_info(r // 4, diffs, med)


def _timing_info(rounds: int, diffs, med: float) -> dict:
    return {"rounds": int(rounds), "repeats": len(diffs),
            "spread_pct": round(100.0 * (max(diffs) - min(diffs))
                                / abs(med), 1)}


def _topologies():
    from flow_updating_tpu.topology.generators import erdos_renyi, fat_tree

    return {
        "fat_tree_k24": fat_tree(24),            # 4,176 nodes / 20,736 edges
        "er_16k": erdos_renyi(16384, avg_degree=8.0, seed=0),
    }


def child(n_devices: int) -> None:
    import jax

    assert len(jax.devices()) >= n_devices, (
        f"{len(jax.devices())} devices, need {n_devices}")

    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.models.state import init_state
    from flow_updating_tpu.models.rounds import node_estimates, run_rounds
    from flow_updating_tpu.parallel import sharded
    from flow_updating_tpu.parallel.mesh import make_mesh
    from flow_updating_tpu.parallel.spmv_sharded import ShardedNodeKernel
    import numpy as np

    S = n_devices
    mesh = make_mesh(S) if S > 1 else None
    results = []
    cfg = RoundConfig.fast(variant="collectall")

    for tname, topo in _topologies().items():
        # single-device reference estimates for correctness at this scale
        k1 = sync.NodeKernel(topo, cfg)
        ref_est = k1.estimates(k1.run(k1.init_state(), 8))

        # -- GSPMD node kernel ------------------------------------------
        kern = sync.NodeKernel(topo, cfg, mesh=mesh)
        st = kern.init_state()
        spr, noisy, tinfo = _time_scan(kern.run, st, 64)
        hlo = (jax.jit(lambda s: kern.run(s, 64))
               .lower(st).compile().as_text())
        est = kern.estimates(kern.run(st, 8))
        np.testing.assert_allclose(est, ref_est, atol=1e-5)
        results.append({
            "path": "gspmd_node", "topology": tname, "shards": S,
            "rounds_per_sec": round(1.0 / spr, 2),
            "timing": tinfo,
            "hlo_collective_bytes": hlo_collective_bytes(hlo),
            **({"noisy": True} if noisy else {}),
        })

        # -- GSPMD node kernel, structured stencil SpMV -----------------
        if topo.structure is not None:
            scfg = dataclasses.replace(cfg, spmv="structured")
            ks = sync.NodeKernel(topo, scfg, mesh=mesh)
            st = ks.init_state()
            spr, noisy, tinfo = _time_scan(ks.run, st, 64)
            hlo = (jax.jit(lambda s: ks.run(s, 64))
                   .lower(st).compile().as_text())
            est = ks.estimates(ks.run(st, 8))
            np.testing.assert_allclose(est, ref_est, atol=1e-5)
            results.append({
                "path": "gspmd_structured", "topology": tname, "shards": S,
                "rounds_per_sec": round(1.0 / spr, 2),
                "timing": tinfo,
                "hlo_collective_bytes": hlo_collective_bytes(hlo),
                **({"noisy": True} if noisy else {}),
            })

        # -- pod-sharded fat-tree stencil (shard_map, one k/2-element
        #    psum per round) ---------------------------------------------
        from flow_updating_tpu.ops.structured import FatTreeStruct
        from flow_updating_tpu.parallel.structured_sharded import (
            PodShardedFatTreeKernel,
        )

        if (mesh is not None and isinstance(topo.structure, FatTreeStruct)
                and topo.structure.k % S == 0):
            kp = PodShardedFatTreeKernel(
                topo, dataclasses.replace(cfg, spmv="structured"), mesh)
            st = kp.init_state()
            spr, noisy, tinfo = _time_scan(kp.run, st, 64)
            hlo = (jax.jit(lambda s: kp.run(s, 64))
                   .lower(st).compile().as_text())
            est = kp.estimates(kp.run(st, 8))
            np.testing.assert_allclose(est, ref_est, atol=1e-5)
            results.append({
                "path": "pod_structured", "topology": tname, "shards": S,
                "rounds_per_sec": round(1.0 / spr, 2),
                "timing": tinfo,
                "hlo_collective_bytes": hlo_collective_bytes(hlo),
                **({"noisy": True} if noisy else {}),
            })

        # -- sharded fused-circuit SpMV (shard_map) ---------------------
        if mesh is not None:
            kb = ShardedNodeKernel(
                topo, dataclasses.replace(cfg, spmv="benes_fused"), mesh)
            st = kb.init_state()
            spr, noisy, tinfo = _time_scan(kb.run, st, 16)
            hlo = (jax.jit(lambda s: kb.run(s, 16))
                   .lower(st).compile().as_text())
            est = kb.estimates(kb.run(st, 8))
            np.testing.assert_allclose(est, ref_est, atol=1e-5)
            results.append({
                "path": "sharded_fused", "topology": tname, "shards": S,
                "rounds_per_sec": round(1.0 / spr, 2),
                "timing": tinfo,
                "hlo_collective_bytes": hlo_collective_bytes(hlo),
                **({"noisy": True} if noisy else {}),
            })

        # -- shard_map halo kernel (edge state), both exchanges, both
        #    fast protocol modes (collect-all messages; pairwise's direct
        #    endpoint-estimate exchange) -------------------------------
        if mesh is not None:
            for pcfg, pname in (
                (cfg, ""),
                (RoundConfig.fast(variant="pairwise"), "_fastpair"),
            ):
                ref_state = init_state(topo, pcfg)
                ref_arrays = topo.device_arrays(
                    coloring=pcfg.needs_coloring)
                eref = np.asarray(node_estimates(
                    run_rounds(ref_state, ref_arrays, pcfg, 4),
                    ref_arrays))
                plan = sharded.plan_sharding(
                    topo, S, partition="bfs",
                    coloring=pcfg.needs_coloring)
                planned = plan.collective_bytes_per_round()
                spr_by_mode = {}
                for halo in ("ppermute", "allgather", "overlap",
                             "interior"):
                    st = sharded.init_plan_state(plan, pcfg, mesh)

                    def run(s, n, _h=halo, _c=pcfg, _p=plan):
                        if _h == "interior":
                            fn, args, _ = sharded.round_program(
                                s, _p, _c, mesh, n, halo=_h,
                                _internal=True)
                            return fn(*args)
                        return sharded.run_rounds_sharded(
                            s, _p, _c, mesh, n, halo=_h)

                    spr, noisy, tinfo = _time_scan(run, st, 8)
                    spr_by_mode[halo] = spr
                    if halo == "interior":
                        # timing-only probe (exchange elided): feeds the
                        # overlap row's ratio, never a row of its own
                        continue
                    hlo = (jax.jit(lambda s: run(s, 8))
                           .lower(st).compile().as_text())
                    est = sharded.gather_estimates(run(st, 4), plan)
                    np.testing.assert_allclose(est, eref, atol=1e-5)
                    results.append({
                        "path": f"halo_{halo}{pname}", "topology": tname,
                        "shards": S,
                        "rounds_per_sec": round(1.0 / spr, 2),
                        "timing": tinfo,
                        "hlo_collective_bytes": hlo_collective_bytes(hlo),
                        "planned_bytes": {
                            "per_round": planned[f"{halo}_bytes"],
                            "cut_fraction": planned["cut_fraction"],
                        },
                        **({"noisy": True} if noisy else {}),
                    })
                _attach_overlap_ratio(results, spr_by_mode, tname, S,
                                      pname)

    print("RESULTS " + json.dumps(results))


def _attach_overlap_ratio(results, spr_by_mode, tname, S, pname="") -> None:
    """Write ``overlap_ratio`` onto the just-recorded overlap row:
    (t_ppermute - t_overlap) / (t_ppermute - t_interior), clamped to
    [0, 1] — the fraction of the serialized exchange the split schedule
    hid.  None when the wire cost is inside timing noise."""
    from flow_updating_tpu.obs.profile import overlap_ratio_from_times

    pp = spr_by_mode.get("ppermute")
    ov = spr_by_mode.get("overlap")
    it = spr_by_mode.get("interior")
    if pp is None or ov is None or it is None:
        return
    exchange, _hidden, ratio = overlap_ratio_from_times(pp, ov, it)
    for r in reversed(results):
        if r["path"] == f"halo_overlap{pname}" and r["topology"] == tname \
                and r["shards"] == S:
            r["overlap_ratio"] = (round(ratio, 3)
                                  if ratio is not None else None)
            r["exchange_sec_per_round"] = round(exchange, 6)
            return


def child_weak(S: int, per_shard: int, smoke: bool = False) -> None:
    """One weak-scaling rung: an ER graph of ``per_shard * S`` nodes
    (degree 8), so the ideal rounds/s curve is FLAT across S.  Rows
    carry ``ladder: 'weak'``; the parent attaches
    ``per_chip_efficiency = rate_S / rate_1`` after merging.  At
    ``S >= 2`` the halo rows cover all three exchange schedules and the
    overlap row records its overlap ratio; ``smoke`` additionally
    asserts the overlap schedule is BIT-identical to ppermute (the CI
    parity gate) and trims the round counts."""
    import numpy as np

    import jax

    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.parallel import sharded
    from flow_updating_tpu.parallel.mesh import make_mesh
    from flow_updating_tpu.topology.generators import erdos_renyi

    cfg = RoundConfig.fast(variant="collectall")
    topo = erdos_renyi(per_shard * S, avg_degree=8.0, seed=0)
    tname = f"er_weak{per_shard}"
    base = {"topology": tname, "shards": S, "ladder": "weak",
            "nodes": topo.num_nodes, "directed_edges": topo.num_edges,
            "per_shard_nodes": per_shard}
    results = []
    r0 = 8 if smoke else 16

    # single-device edge-kernel reference for correctness at this scale
    k1 = sync.NodeKernel(topo, cfg)
    ref_est = k1.estimates(k1.run(k1.init_state(), 8))

    # GSPMD node kernel (mesh only when sharded)
    kern = sync.NodeKernel(topo, cfg, mesh=make_mesh(S) if S > 1 else None)
    st = kern.init_state()
    spr, noisy, tinfo = _time_scan(kern.run, st, 4 * r0)
    hlo = (jax.jit(lambda s: kern.run(s, 16)).lower(st).compile()
           .as_text())
    np.testing.assert_allclose(kern.estimates(kern.run(st, 8)), ref_est,
                               atol=1e-5)
    results.append({
        "path": "gspmd_node", **base,
        "rounds_per_sec": round(1.0 / spr, 2),
        "timing": tinfo,
        "hlo_collective_bytes": hlo_collective_bytes(hlo),
        **({"noisy": True} if noisy else {}),
    })

    # halo kernel, all exchange schedules (S=1 runs on a 1-device mesh:
    # the same program with no wire — the weak ladder's baseline)
    mesh = make_mesh(S)
    plan = sharded.plan_sharding(topo, S, partition="bfs")
    planned = plan.collective_bytes_per_round()
    eref = sharded.gather_estimates(
        sharded.run_rounds_sharded(
            sharded.init_plan_state(plan, cfg, mesh), plan, cfg, mesh, 4),
        plan)
    np.testing.assert_allclose(
        eref, np.asarray(k1.estimates(k1.run(k1.init_state(), 4))),
        atol=1e-5)
    spr_by_mode = {}
    states = {}
    for halo in ("ppermute", "allgather", "overlap", "interior"):
        st = sharded.init_plan_state(plan, cfg, mesh)

        def run(s, n, _h=halo):
            if _h == "interior":
                fn, args, _ = sharded.round_program(
                    s, plan, cfg, mesh, n, halo=_h, _internal=True)
                return fn(*args)
            return sharded.run_rounds_sharded(
                s, plan, cfg, mesh, n, halo=_h)

        spr, noisy, tinfo = _time_scan(run, st, r0)
        spr_by_mode[halo] = spr
        if halo == "interior":
            continue
        hlo = (jax.jit(lambda s, _r=run: _r(s, 8)).lower(st).compile()
               .as_text())
        out = run(st, 4)
        states[halo] = out
        np.testing.assert_allclose(
            sharded.gather_estimates(out, plan), eref, atol=1e-5)
        results.append({
            "path": f"halo_{halo}", **base,
            "rounds_per_sec": round(1.0 / spr, 2),
            "timing": tinfo,
            "hlo_collective_bytes": hlo_collective_bytes(hlo),
            "planned_bytes": {
                "per_round": planned[f"{halo}_bytes"],
                "cut_fraction": planned["cut_fraction"],
            },
            **({"noisy": True} if noisy else {}),
        })
    _attach_overlap_ratio(results, spr_by_mode, tname, S)

    if smoke and S > 1:
        # the CI parity gate: the overlap schedule's final state is
        # BIT-identical to the serialized ppermute oracle's
        for a, b in zip(jax.tree_util.tree_leaves(states["ppermute"]),
                        jax.tree_util.tree_leaves(states["overlap"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        print("SMOKE overlap==ppermute bit-parity OK", file=sys.stderr)

    print("RESULTS " + json.dumps(results))


def child_mega(S: int, k: int) -> None:
    """Mega-scale pod-stencil evidence (VERDICT r4 item 8): the 66M-node
    design point's sharding, exercised at the largest scale a CPU mesh
    can hold — a VIRTUAL fat-tree (no edge arrays; k=344 is 10.3M nodes)
    at S shards.  Records rounds/s shape + HLO collective bytes and
    asserts estimate parity against the single-device structured kernel.
    CPU wall-clock is not a TPU prediction; the evidence is that the
    sharded program compiles, executes, matches, and moves O(k) bytes
    per round regardless of node count."""
    import numpy as np

    import jax

    from flow_updating_tpu.models import sync
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.ops.structured import FatTreeStruct
    from flow_updating_tpu.parallel.mesh import make_mesh
    from flow_updating_tpu.parallel.structured_sharded import (
        PodShardedFatTreeKernel,
    )
    from flow_updating_tpu.topology.generators import fat_tree

    assert len(jax.devices()) >= S, f"{len(jax.devices())} devices < {S}"
    mesh = make_mesh(S)
    cfg = RoundConfig.fast(variant="collectall", kernel="node",
                           spmv="structured")
    topo = fat_tree(k, seed=0, materialize_edges=False)
    assert isinstance(topo.structure, FatTreeStruct)
    assert topo.structure.k % S == 0, (k, S)
    tname = f"fat_tree_k{k}_virtual"
    results = []

    # single-device structured reference at the same scale
    k1 = sync.NodeKernel(topo, cfg)
    ref_est = k1.estimates(k1.run(k1.init_state(), 8))

    runs = [("pod_structured",
             PodShardedFatTreeKernel(topo, cfg, mesh)),
            ("gspmd_structured",
             sync.NodeKernel(topo, cfg, mesh=mesh))]
    for path, kern in runs:
        st = kern.init_state()
        spr, noisy, tinfo = _time_scan(kern.run, st, 8)
        hlo = (jax.jit(lambda s, _k=kern: _k.run(s, 8))
               .lower(st).compile().as_text())
        est = kern.estimates(kern.run(st, 8))
        # fp32 at 10M+ nodes: sharded stencil reductions accumulate in a
        # different order than the single-device kernel; observed max
        # deviation 1.5e-5 on values ~0.5 (0.27% of elements past 1e-5).
        # 5e-5 still catches any semantic error by orders of magnitude.
        np.testing.assert_allclose(est, ref_est, atol=5e-5)
        results.append({
            "path": path, "topology": tname, "shards": S,
            "nodes": topo.num_nodes,
            "rounds_per_sec": round(1.0 / spr, 2),
            "timing": tinfo,
            "hlo_collective_bytes": hlo_collective_bytes(hlo),
            **({"noisy": True} if noisy else {}),
        })

    print("RESULTS " + json.dumps(results))


def _merge_keep_best(out_path: str, fresh: list) -> list:
    """Merge fresh rows into a banked artifact, keeping the best
    measurement per (path, topology, shards).

    Same code on the same harness: a slower wall-clock is contention
    noise, so higher rounds/s is the better measurement — and a clean
    (non-noisy) row always beats a noisy one (numbers-of-record
    convention; a degraded re-run must never clobber a good banked
    row)."""
    banked = {}
    try:
        with open(out_path) as f:
            for r in json.load(f).get("results", []):
                banked[(r["path"], r["topology"], r["shards"])] = r
    except (OSError, json.JSONDecodeError, KeyError, TypeError):
        pass
    for r in fresh:
        key = (r["path"], r["topology"], r["shards"])
        old = banked.get(key)
        if old is None:
            banked[key] = r
            continue
        old_clean = not old.get("noisy")
        new_clean = not r.get("noisy")
        if (new_clean, r["rounds_per_sec"]) >= (
                old_clean, old["rounds_per_sec"]):
            banked[key] = r
    return sorted(banked.values(),
                  key=lambda r: (r["topology"], r["path"], r["shards"]))


def _attach_weak_efficiency(rows) -> None:
    """``per_chip_efficiency`` for every multi-shard weak-ladder row:
    rate_S / rate_1 of the same (path, topology) — weak scaling's ideal
    is a flat rounds/s curve, so 1.0 is perfect.  A noisy S=1 row is a
    degraded denominator and never anchors the ratio (the same
    quarantine the regress gate applies to the rows themselves); any
    stale efficiency from a previous merge is dropped with it."""
    base = {}
    for r in rows:
        if r.get("ladder") == "weak" and r.get("shards") == 1 \
                and not r.get("noisy"):
            base[(r["path"], r["topology"])] = r["rounds_per_sec"]
    for r in rows:
        if r.get("ladder") != "weak" or r.get("shards", 1) <= 1:
            continue
        b = base.get((r["path"], r["topology"]))
        if b:
            r["per_chip_efficiency"] = round(r["rounds_per_sec"] / b, 4)
        else:
            r.pop("per_chip_efficiency", None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", type=int, default=0)
    ap.add_argument("--shards", default="1,2,4,8")
    ap.add_argument("--weak", type=int, default=0, metavar="N",
                    help="run the weak-scaling ladder at N nodes PER "
                         "SHARD (ER degree 8; topology grows with S so "
                         "the ideal rounds/s curve is flat) — rows gain "
                         "per_chip_efficiency and the overlap rows an "
                         "overlap_ratio")
    ap.add_argument("--weak-only", action="store_true",
                    help="skip the standard fixed-topology ladder; run "
                         "only the --weak rungs")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: 2-shard weak ladder (2048 nodes/"
                         "shard unless --weak overrides), overlap-vs-"
                         "ppermute BIT-parity asserted in-child; "
                         "implies --weak-only --shards 1,2")
    ap.add_argument("--mega-k", type=int, default=0,
                    help="also run the mega-scale virtual fat-tree "
                         "section (pod/gspmd structured only) at this "
                         "arity on the LARGEST shard count (e.g. 344 = "
                         "10.3M nodes at S=8)")
    ap.add_argument("--mega-only", action="store_true",
                    help="skip the standard S-ladder; run only --mega-k")
    ap.add_argument("--out", default=os.path.join(
        REPO, "MULTICHIP_SCALING_r6.json"))
    args = ap.parse_args(argv)

    if args.smoke:
        args.weak = args.weak or 2048
        args.weak_only = True
        args.shards = "1,2"

    if args.child:
        if args.mega_k:
            child_mega(args.child, args.mega_k)
        elif args.weak:
            child_weak(args.child, args.weak, smoke=args.smoke)
        else:
            child(args.child)
        return 0

    sys.path.insert(0, REPO)
    from flow_updating_tpu.utils.backend import cpu_subprocess_env

    shard_list = [int(s) for s in args.shards.split(",")]
    jobs = []
    if not (args.mega_only or args.weak_only):
        jobs += [(S, []) for S in shard_list]
    if args.weak:
        weak_flags = ["--weak", str(args.weak)]
        if args.smoke:
            weak_flags.append("--smoke")
        jobs += [(S, list(weak_flags)) for S in shard_list]
    if args.mega_k:
        jobs.append((max(shard_list), ["--mega-k", str(args.mega_k)]))

    all_results = []
    for S, extra in jobs:
        env = cpu_subprocess_env(n_virtual_devices=max(S, 2),
                                 extra_path=REPO)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(S),
             *extra],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=3600)
        if proc.returncode != 0:
            print(proc.stdout[-2000:], file=sys.stderr)
            print(proc.stderr[-4000:], file=sys.stderr)
            raise RuntimeError(f"child S={S} failed rc={proc.returncode}")
        # surface noisy-timing warnings even on success — a degraded
        # measurement must be visible to the operator, not just flagged
        # in the JSON row
        for wline in proc.stderr.splitlines():
            if "WARNING" in wline:
                print(f"S={S} {wline}", file=sys.stderr, flush=True)
        for line in proc.stdout.splitlines():
            if line.startswith("RESULTS "):
                all_results.extend(json.loads(line[len("RESULTS "):]))
        print(f"S={S}: done ({len(all_results)} rows total)")

    all_results = _merge_keep_best(args.out, all_results)
    _attach_weak_efficiency(all_results)
    out = {
        "meta": {
            "harness": "virtual CPU mesh (xla_force_host_platform_device_"
                       "count); wall-clock is curve-shape evidence, not a "
                       "TPU prediction — see scripts/multichip_scaling.py",
            "timing": "R-vs-2R scan difference (median of 5; rows with "
                      "'noisy': true never met the spread gate and never "
                      "displace a banked clean row)",
            "correctness": "every row's estimates checked against the "
                           "single-device kernel (atol 1e-5); --smoke "
                           "additionally asserts overlap==ppermute "
                           "BIT-parity in-child",
            "efficiency": "weak-ladder rows (ladder: weak) carry "
                          "per_chip_efficiency = rate_S / rate_1 (ideal "
                          "weak scaling is flat); overlap rows carry "
                          "overlap_ratio = hidden/serialized exchange "
                          "time; both gated by `regress` against the "
                          "MULTICHIP_SCALING_* history",
        },
        "results": all_results,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    # human-readable table
    print(f"\n{'path':<16}{'topology':<14}{'S':>3}{'rounds/s':>12}"
          f"{'hlo coll. B':>14}{'eff':>7}{'ovl':>6}")
    for r in all_results:
        eff = r.get("per_chip_efficiency")
        ovl = r.get("overlap_ratio")
        print(f"{r['path']:<16}{r['topology']:<14}{r['shards']:>3}"
              f"{r['rounds_per_sec']:>12}"
              f"{r['hlo_collective_bytes']['total']:>14}"
              f"{(f'{eff:.2f}' if eff is not None else '-'):>7}"
              f"{(f'{ovl:.2f}' if ovl is not None else '-'):>6}")
    print(f"\nwrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
