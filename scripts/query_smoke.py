#!/usr/bin/env python
"""CI query-fabric smoke: the multi-tenant lane engine's acceptance
scenario on the CPU proxy (ISSUE 12; docs/QUERY.md).

1. build a ``QueryFabric`` on a >= 100k-node-capacity engine with
   ``--lanes`` concurrent-query lanes (CI default 64; the full
   acceptance run is ``--lanes 1024``);
2. offer ~1.5x lanes queries under Poisson arrival while membership
   churn (join/add-edge/leave) runs between segments — asserting the
   round program compiles EXACTLY once across every admission,
   retirement and membership event;
3. assert at least one retired lane was RECYCLED (a lane that served
   one query admitted a second);
4. write the ``flow-updating-query-report/v1`` manifest and run
   ``doctor`` over it — lane compile-count, per-lane mass SLO (free
   lanes exactly 0.0), admission-latency SLO.

Exit code: the doctor's (0 healthy; 1 on any failing check), or 1 on
any assertion above.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--nodes", type=int, default=99_000,
                    help="initial members (erdos_renyi:N:6)")
    ap.add_argument("--capacity", type=int, default=100_000,
                    help="node-slot capacity (acceptance floor: 100k)")
    ap.add_argument("--lanes", type=int, default=64,
                    help="concurrent-query lanes (acceptance run: 1024)")
    ap.add_argument("--queries", type=int, default=0,
                    help="queries to offer (default: 1.5x lanes, so "
                         "retired lanes must recycle)")
    ap.add_argument("--events", type=int, default=24,
                    help="membership/edge churn events interleaved "
                         "between segments")
    ap.add_argument("--segment-rounds", type=int, default=8)
    ap.add_argument("--eps", type=float, default=1e-2,
                    help="per-query retirement tolerance (the smoke "
                         "checks lane mechanics, not precision)")
    ap.add_argument("--max-rounds", type=int, default=4096)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.models.rounds import run_rounds
    from flow_updating_tpu.obs.report import (
        build_query_manifest,
        write_report,
    )
    from flow_updating_tpu.query import QueryFabric
    from flow_updating_tpu.topology.generators import erdos_renyi

    queries = args.queries or (args.lanes + args.lanes // 2)
    t0 = time.perf_counter()
    topo = erdos_renyi(args.nodes, avg_degree=6.0, seed=0)
    fab = QueryFabric(topo, lanes=args.lanes, capacity=args.capacity,
                      degree_budget=24, segment_rounds=args.segment_rounds,
                      seed=0, conv_eps=args.eps)
    print(f"query_smoke: capacity {fab.svc.capacity} nodes x "
          f"{fab.lanes} lanes, {fab.svc.live_count} members, built in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    cache0 = run_rounds._cache_size()
    rng = np.random.default_rng(0)
    members = fab.svc.live_ids()
    held: list = []
    submitted = events = rounds = 0
    while (submitted < queries or fab.active_lanes or fab.queued) \
            and rounds < args.max_rounds:
        arrivals = min(int(rng.poisson(0.5 * args.lanes)),
                       queries - submitted)
        for _ in range(arrivals):
            m = int(rng.integers(8, 64))
            cohort = rng.choice(members, size=m, replace=False)
            fab.submit(rng.random(m), cohort=np.sort(cohort))
            submitted += 1
        boundary_budget = 6
        while events < args.events and boundary_budget > 0:
            # membership churn between segments: join + wire in, or a
            # previously joined member leaves
            if held and rng.random() < 0.4:
                fab.leave([held.pop()])
                events += 1
                boundary_budget -= 1
            else:
                slot = fab.join()
                a = int(rng.integers(0, args.nodes))
                fab.add_edges([(slot, a)])
                held.append(slot)
                events += 2
                boundary_budget -= 2
        fab.run(args.segment_rounds)
        rounds += args.segment_rounds

    compiles = run_rounds._cache_size() - cache0
    if compiles != 1:
        print(f"query_smoke: round program compiled {compiles}x over "
              f"{submitted} queries + {events} membership events "
              "(expected exactly 1)", file=sys.stderr)
        return 1
    if fab.retired_total < queries:
        print(f"query_smoke: only {fab.retired_total}/{queries} queries "
              f"retired within {rounds} rounds", file=sys.stderr)
        return 1
    lanes_used: dict = {}
    for q in fab._queries.values():
        if q["lane"] is not None:
            lanes_used[q["lane"]] = lanes_used.get(q["lane"], 0) + 1
    recycled = sum(1 for n in lanes_used.values() if n > 1)
    if recycled == 0:
        print("query_smoke: no retired lane was recycled (every query "
              "got a fresh lane — raise queries vs lanes)",
              file=sys.stderr)
        return 1
    resid = fab.mass_residual()
    print(f"query_smoke: {submitted} queries through {args.lanes} lanes "
          f"({recycled} lanes recycled), {events} membership events, "
          f"{rounds} rounds, 1 compile, "
          f"max|free-lane residual|={float(np.max(np.abs(resid))):.3e}, "
          f"{time.perf_counter() - t0:.1f}s total", file=sys.stderr)

    manifest_path = os.path.join(args.outdir, "query_report.json")
    write_report(manifest_path, build_query_manifest(
        argv=sys.argv[1:], config=fab.svc.config, topo=topo,
        query=fab.query_block()))
    return cli_main(["doctor", manifest_path])


if __name__ == "__main__":
    sys.exit(main())
