#!/usr/bin/env python
"""CI aggregate-algebra smoke: every aggregate kind concurrent on ONE
fabric program on the CPU proxy (ISSUE 16; docs/AGGREGATES.md).

1. build an ``AggregateFabric`` (10k-node ER membership by default)
   with probe-row recording on;
2. submit all five kinds CONCURRENTLY — a sum/count pair, max + min
   consensus lanes, an ε-quantile bracket bank and a standing windowed
   mean — then drive scan segments while membership churn
   (join/add-edge/leave of non-cohort members) runs between segments,
   pushing fresh sample batches through the standing window;
3. admit a second mixed wave into the retired lanes (extrema lanes must
   recycle), asserting the round program compiled at most twice: the
   plain program plus the one-time extrema ``lane_modes`` install;
4. check every kind's read against its host oracle (extrema near-exact,
   quantile within ``qeps * (hi - lo)``, sum/count within its own
   error bound);
5. write the ``flow-updating-query-report/v1`` manifest with the
   ``aggregates`` block + probe rows and run ``doctor`` over it —
   per-kind read contracts, extrema monotonicity, kind census, lane
   compile-count, per-lane mass SLO.

Exit code: the doctor's (0 healthy; 1 on any failing check), or 1 on
any assertion above.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--nodes", type=int, default=10_000,
                    help="initial members (erdos_renyi:N:6)")
    ap.add_argument("--capacity", type=int, default=0,
                    help="node-slot capacity (default: nodes + 64 "
                         "churn headroom)")
    ap.add_argument("--lanes", type=int, default=32,
                    help="payload lanes shared by every kind")
    ap.add_argument("--events", type=int, default=16,
                    help="membership/edge churn events interleaved "
                         "between segments")
    ap.add_argument("--segment-rounds", type=int, default=8)
    ap.add_argument("--eps", type=float, default=1e-3,
                    help="mean-lane retirement tolerance")
    ap.add_argument("--qeps", type=float, default=0.34,
                    help="quantile rank tolerance (3 bracket lanes)")
    ap.add_argument("--max-rounds", type=int, default=4096)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from flow_updating_tpu.aggregates import AggregateFabric
    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.models.rounds import run_rounds
    from flow_updating_tpu.obs.report import (
        build_query_manifest,
        write_report,
    )
    from flow_updating_tpu.topology.generators import erdos_renyi

    capacity = args.capacity or args.nodes + 64
    t0 = time.perf_counter()
    topo = erdos_renyi(args.nodes, avg_degree=6.0, seed=0)
    fab = AggregateFabric(topo, lanes=args.lanes, capacity=capacity,
                          degree_budget=24,
                          segment_rounds=args.segment_rounds, seed=0,
                          conv_eps=args.eps, probe_manifest=True)
    print(f"aggregate_smoke: capacity {fab.svc.capacity} nodes x "
          f"{fab.lanes} lanes, {fab.svc.live_count} members, built in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    cache0 = run_rounds._cache_size()
    rng = np.random.default_rng(0)
    members = fab.svc.live_ids()

    def cohort(m: int):
        return np.sort(rng.choice(members, size=m, replace=False))

    def submit_wave(tag: str) -> dict:
        """One of each value kind over its own cohort + values; returns
        {label: (aid, cohort_values)} for the oracle checks."""
        out = {}
        for label, kind, params in (
                ("sum_count", "sum_count", {}),
                ("max", "max", {}),
                ("min", "min", {}),
                ("quantile", "quantile",
                 {"q": 0.5, "qeps": args.qeps})):
            c = cohort(int(rng.integers(64, 256)))
            vals = rng.random(c.size)
            aid = fab.submit_aggregate(kind, vals, c, tag=tag,
                                      **params)
            out[label] = (aid, vals)
        return out

    wave1 = submit_wave("wave1")
    win_cohort = cohort(128)
    win_vals = [rng.random(128)]
    win_aid = fab.submit_aggregate("windowed_mean", win_vals[0],
                                   win_cohort, window=4, tag="standing")

    def value_kinds_done(wave: dict) -> bool:
        return all(fab.read_aggregate(aid)["status"] == "done"
                   for aid, _ in wave.values())

    held: list = []
    events = rounds = pushes = 0

    def churn(budget: int) -> None:
        # joins wire in FRESH slots and leaves only remove them again,
        # so every submitted cohort keeps its host oracle valid
        nonlocal events
        while events < args.events and budget > 0:
            if held and rng.random() < 0.4:
                fab.leave([held.pop()])
                events += 1
                budget -= 1
            else:
                slot = fab.join()
                fab.add_edges([(slot, int(rng.integers(0, args.nodes)))])
                held.append(slot)
                events += 2
                budget -= 2

    while not value_kinds_done(wave1) and rounds < args.max_rounds:
        churn(6)
        if pushes < 3 and rounds and rounds % (4 * args.segment_rounds) == 0:
            batch = rng.random(128)
            win_vals.append(batch)
            fab.push(win_aid, batch)
            pushes += 1
        fab.run(args.segment_rounds)
        rounds += args.segment_rounds

    # second wave: the freed lanes (extrema retire in ~diameter rounds)
    # must recycle under the SAME program — mode flips are value edits
    wave2 = submit_wave("wave2")
    while (not value_kinds_done(wave2) and rounds < 2 * args.max_rounds):
        churn(6)
        fab.run(args.segment_rounds)
        rounds += args.segment_rounds

    compiles = run_rounds._cache_size() - cache0
    if compiles > 2 or fab.compile_count > 2:
        print(f"aggregate_smoke: round program compiled {compiles}x "
              f"(fabric accounting {fab.compile_count}) across 2 mixed "
              "waves + churn (budget: plain program + one extrema "
              "lane_modes install = 2)", file=sys.stderr)
        return 1
    for name, wave in (("wave1", wave1), ("wave2", wave2)):
        if not value_kinds_done(wave):
            print(f"aggregate_smoke: {name} not done within {rounds} "
                  "rounds", file=sys.stderr)
            return 1
        for label, (aid, vals) in wave.items():
            read = fab.read_aggregate(aid, max_staleness=None)
            res = read["result"]
            got = float(res["mean"] if label == "sum_count"
                        else res["value"])
            truth = {"sum_count": float(np.mean(vals)),
                     "max": float(np.max(vals)),
                     "min": float(np.min(vals)),
                     "quantile": float(np.sort(vals)[
                         int(np.ceil(0.5 * vals.size)) - 1])}[label]
            if label == "sum_count":
                bound = float(res["mean_error_bound"]) + 1e-9
            elif label == "quantile":
                bound = args.qeps * (float(res["hi"])
                                     - float(res["lo"])) + 1e-9
            else:
                bound = 1e-6
            if abs(got - truth) > bound:
                print(f"aggregate_smoke: {name}/{label} read {got!r} "
                      f"vs oracle {truth!r} exceeds bound {bound:.3g}",
                      file=sys.stderr)
                return 1
    win_read = fab.read_aggregate(win_aid, max_staleness=None)
    win_truth = float(np.mean(np.concatenate(win_vals[-4:])))
    restreams = len(fab._aggs[win_aid]["restreams"])
    if restreams < pushes:
        print(f"aggregate_smoke: standing window restreamed "
              f"{restreams}x for {pushes} pushes", file=sys.stderr)
        return 1

    kinds = fab.aggregate_block()["kinds"]
    print(f"aggregate_smoke: {len(kinds)} kinds "
          f"({', '.join(sorted(kinds))}) over {fab.lanes} lanes, "
          f"{events} membership events, {rounds} rounds, {compiles} "
          f"compile(s), window mean {float(win_read['result']['mean']):.4f} "
          f"(host {win_truth:.4f}, {pushes} pushes), "
          f"{time.perf_counter() - t0:.1f}s total", file=sys.stderr)

    manifest_path = os.path.join(args.outdir, "aggregate_report.json")
    write_report(manifest_path, build_query_manifest(
        argv=sys.argv[1:], config=fab.svc.config, topo=topo,
        query=fab.query_block(),
        extra={"aggregates": fab.aggregate_block()}))
    return cli_main(["doctor", manifest_path])


if __name__ == "__main__":
    sys.exit(main())
