#!/usr/bin/env python
"""CI convergence-observatory smoke: mixing estimation, per-query ETA
forecasts and forecast-aware admission on the CPU proxy (ISSUE 20;
docs/OBSERVABILITY.md §10).

1. estimate the er2048 graph's spectral gap (both provenances, autotune
   cached — a second report must be a pure cache hit), then drive a
   forecasting ``QueryFabric`` through >= 16 cohort queries under
   membership churn: every active read past the warmup window must
   carry an ETA, the round program must compile exactly once, and the
   banked ``forecast_ratio`` population must be >= 90% inside the
   declared [1/band, band];
2. write the ``flow-updating-query-report/v1`` manifest (forecast block
   + mixing block embedded) and pass ``doctor --strict`` over it —
   ``forecast_calibrated``, ``slo_admission``, ``mixing_sane``,
   ``span_complete`` and ``metrics_consistency`` included;
3. the NEGATIVE control — the same manifest with a forged
   ``forecast_ratio = 25`` planted in the ratio bank — must FAIL
   ``forecast_calibrated`` specifically: doctor can tell a calibrated
   forecaster from a lying one;
4. the scenario pair: ``bridge_bottleneck``'s community graph must
   carry a spectral gap predicting >= 2x the rounds of its
   expander-augmented ``expander_relief`` control, doctor-asserted
   from the persisted mixing records (ROADMAP item 4, now a gate);
5. strict admission: against the bridge graph's own mixing record and
   an SLO it provably cannot meet, every query is DEFERRED at the door
   (``submitted -> deferred`` chains, zero lanes held, zero compiles
   wasted) and the Perfetto export renders the deferrals.

Exit code: 0 only if every assertion above holds.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--nodes", type=int, default=2048,
                    help="er fabric member count (acceptance: 2048)")
    ap.add_argument("--lanes", type=int, default=8)
    ap.add_argument("--queries", type=int, default=20,
                    help="queries to offer (acceptance floor: 16)")
    ap.add_argument("--events", type=int, default=12,
                    help="membership churn events between segments")
    ap.add_argument("--segment-rounds", type=int, default=4)
    ap.add_argument("--eps", type=float, default=1e-4)
    ap.add_argument("--max-rounds", type=int, default=4096)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    cache = os.path.join(args.outdir, "forecast_autotune_cache.json")
    os.environ["FLOW_UPDATING_AUTOTUNE_CACHE"] = cache

    import numpy as np

    from flow_updating_tpu.cli import main as cli_main
    from flow_updating_tpu.obs import health
    from flow_updating_tpu.obs.forecast import FORECAST_BAND
    from flow_updating_tpu.obs.report import (
        build_query_manifest,
        write_report,
    )
    from flow_updating_tpu.obs.spectral import mixing_report
    from flow_updating_tpu.query import QueryFabric
    from flow_updating_tpu.scenarios.registry import (
        _community,
        _expander,
    )
    from flow_updating_tpu.topology.generators import erdos_renyi

    # -- 1: mixing estimate + the forecasting churn run -------------------
    t0 = time.perf_counter()
    topo = erdos_renyi(args.nodes, avg_degree=6.0, seed=0)
    mix = mixing_report(topo, eps=args.eps)
    if mix["cache"]["hit"] or not mixing_report(
            topo, eps=args.eps)["cache"]["hit"]:
        print("forecast_smoke: mixing cache discipline broken (first "
              "report must miss, second must hit)", file=sys.stderr)
        return 1
    if not (0.0 < mix["gap"] <= 1.0):
        print(f"forecast_smoke: er{args.nodes} gap {mix['gap']} out of "
              "range", file=sys.stderr)
        return 1
    print(f"forecast_smoke: er{args.nodes} gap {mix['gap']:.4f} "
          f"({mix['provenance']}) -> ~{mix['predicted_rounds']:.0f} "
          f"rounds to eps={args.eps:g}", file=sys.stderr)

    fab = QueryFabric(topo, lanes=args.lanes, capacity=args.nodes + 64,
                      degree_budget=24,
                      segment_rounds=args.segment_rounds, seed=0,
                      conv_eps=args.eps, mixing=mix,
                      admission_slo_rounds=64 * args.segment_rounds,
                      convergence_slo_rounds=64 * args.segment_rounds)
    rng = np.random.default_rng(0)
    members = fab.svc.live_ids()
    held: list = []
    submitted = events = rounds = eta_reads = 0
    while (submitted < args.queries or fab.active_lanes or fab.queued) \
            and rounds < args.max_rounds:
        arrivals = min(int(rng.poisson(0.5 * args.lanes)),
                       args.queries - submitted)
        for _ in range(arrivals):
            m = int(rng.integers(8, 64))
            cohort = rng.choice(members, size=m, replace=False)
            fab.submit(rng.random(m), cohort=np.sort(cohort))
            submitted += 1
        if events < args.events:
            if held and rng.random() < 0.4:
                fab.leave([held.pop()])
            else:
                slot = fab.join()
                fab.add_edges([(slot, int(rng.integers(0, args.nodes)))])
                held.append(slot)
            events += 1
        fab.run(args.segment_rounds)
        rounds += args.segment_rounds
        # the ETA read contract, live: every active query's read names
        # a forecast status, and a warm one prices the remaining rounds
        for qid, q in fab._queries.items():
            if q["status"] != "active":
                continue
            r = fab.read(qid, max_staleness=0)
            if "forecast_status" not in r:
                print(f"forecast_smoke: active read of qid {qid} has "
                      "no forecast_status", file=sys.stderr)
                return 1
            if r["forecast_status"] == "ok":
                if not (r["eta_rounds"] >= 0.0
                        and r["eta_lo"] <= r["eta_hi"]):
                    print(f"forecast_smoke: malformed ETA on qid "
                          f"{qid}: {r}", file=sys.stderr)
                    return 1
                eta_reads += 1
    if fab.retired_total < args.queries:
        print(f"forecast_smoke: only {fab.retired_total}/"
              f"{args.queries} queries retired in {rounds} rounds",
              file=sys.stderr)
        return 1
    if eta_reads == 0:
        print("forecast_smoke: no warm ETA was ever served",
              file=sys.stderr)
        return 1
    if fab.compile_count > 1:
        print(f"forecast_smoke: forecasting broke the compile budget "
              f"({fab.compile_count} > 1)", file=sys.stderr)
        return 1
    fore = fab.query_block()["forecast"]
    ratios = fore["ratios"]
    in_band = fore["in_band_frac"]
    print(f"forecast_smoke: {submitted} queries / {events} churn "
          f"events / {rounds} rounds, {eta_reads} warm ETA reads, "
          f"{len(ratios)} ratios (p90 |log| "
          f"{fore['p90_abs_log_ratio']:.3f}, {100 * in_band:.0f}% in "
          f"band), {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    if len(ratios) < args.queries // 2 or in_band is None \
            or in_band < 0.9:
        print(f"forecast_smoke: calibration floor missed — need >= 90% "
              f"of ratios in [1/{FORECAST_BAND:g}, {FORECAST_BAND:g}]",
              file=sys.stderr)
        return 1

    # -- 2: manifest + doctor --strict -------------------------------------
    manifest_path = os.path.join(args.outdir, "forecast_report.json")
    write_report(manifest_path, build_query_manifest(
        argv=sys.argv[1:], config=fab.svc.config, topo=topo,
        query=fab.query_block(),
        extra={"serving_trace": fab.serving_trace_block(),
               "mixing": mix}))
    rc = cli_main(["doctor", manifest_path, "--strict"])
    if rc != 0:
        print("forecast_smoke: doctor --strict FAILED on the honest "
              "forecast manifest", file=sys.stderr)
        return 1

    # -- 3: the forged-ratio negative control ------------------------------
    with open(manifest_path) as f:
        forged = json.load(f)
    forged["query"]["forecast"]["ratios"] = (
        list(forged["query"]["forecast"]["ratios"])[:-1] + [25.0])
    forged_path = os.path.join(args.outdir,
                               "forecast_forged_report.json")
    with open(forged_path, "w") as f:
        json.dump(forged, f)
    by = {c.name: c.status
          for c in health.diagnose_manifest(forged)}
    if cli_main(["doctor", forged_path]) == 0 \
            or by.get("forecast_calibrated") != health.FAIL:
        print(f"forecast_smoke: forged forecast_ratio=25 did not fail "
              f"forecast_calibrated: {by}", file=sys.stderr)
        return 1
    print("forecast_smoke: forged ratio failed forecast_calibrated as "
          "designed", file=sys.stderr)

    # -- 4: the scenario pair, doctor-asserted -----------------------------
    t1 = time.perf_counter()
    bridge_topo = _community(0)
    bridge = mixing_report(bridge_topo, eps=args.eps)
    relief = mixing_report(_expander(0), eps=args.eps)
    slowdown = bridge["predicted_rounds"] / relief["predicted_rounds"]
    bridge["control"] = {"name": "expander_relief",
                         "gap": relief["gap"], "min_factor": 2.0}
    verdicts = health.check_mixing(bridge)
    print(f"forecast_smoke: bridge gap {bridge['gap']:.4f} vs relief "
          f"{relief['gap']:.4f} -> {slowdown:.1f}x predicted slowdown "
          f"({time.perf_counter() - t1:.1f}s)", file=sys.stderr)
    if slowdown < 2.0 or verdicts[0].status != health.PASS:
        print(f"forecast_smoke: scenario-pair assertion failed: "
              f"{verdicts[0].summary}", file=sys.stderr)
        return 1

    # -- 5: strict admission against an unmeetable SLO ---------------------
    slo = max(1, int(bridge["predicted_rounds"] / 4))
    strict = QueryFabric(bridge_topo, lanes=4,
                         capacity=bridge_topo.num_nodes + 8,
                         segment_rounds=args.segment_rounds, seed=0,
                         conv_eps=args.eps, mixing=bridge,
                         admit_policy="strict",
                         convergence_slo_rounds=slo)
    for k in range(4):
        strict.submit(float(k + 1))
    strict.run(args.segment_rounds)
    if strict.deferred_total != 4 or strict.active_lanes \
            or strict.compile_count > 1:
        print(f"forecast_smoke: strict admission leg: "
              f"{strict.deferred_total}/4 deferred, "
              f"{strict.active_lanes} lanes held, "
              f"{strict.compile_count} compiles", file=sys.stderr)
        return 1
    strict_path = os.path.join(args.outdir,
                               "forecast_strict_report.json")
    write_report(strict_path, build_query_manifest(
        argv=sys.argv[1:], config=strict.svc.config, topo=bridge_topo,
        query=strict.query_block(),
        extra={"serving_trace": strict.serving_trace_block(),
               "mixing": bridge}))
    if cli_main(["doctor", strict_path, "--strict"]) != 0:
        print("forecast_smoke: doctor --strict FAILED on the strict-"
              "admission manifest", file=sys.stderr)
        return 1
    trace_path = os.path.join(args.outdir, "forecast_strict.trace.json")
    if cli_main(["obs", "export-trace", strict_path,
                 "--output", trace_path]) != 0:
        return 1
    with open(trace_path) as f:
        doc = json.load(f)
    deferred = [e for e in doc["traceEvents"]
                if e.get("ph") == "i" and "deferred" in e.get("name", "")]
    if len(deferred) != 4:
        print(f"forecast_smoke: Perfetto export rendered "
              f"{len(deferred)}/4 deferred instants", file=sys.stderr)
        return 1
    print("forecast_smoke: strict admission deferred 4/4 at the door "
          "and the trace shows it", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
