#!/usr/bin/env python
"""CI observability smoke: profile + fields + doctor on a small topology.

Runs the measurement-to-verdict pillars end to end on CPU and leaves
the manifests in ``--outdir`` (the tier1 workflow uploads them as build
artifacts):

1. ``profile`` — AOT cost attribution of the edge and node kernels on a
   small ring, written as ``flow-updating-profile-report/v1`` manifests;
2. ``run --telemetry --report`` — a real telemetry run manifest;
3. ``inspect`` — two identical-seed per-node/per-edge FIELD recordings
   (``flow-updating-field-report/v1``) with blame, then ``--diff``
   between them — which must report zero deltas;
4. ``doctor`` — judges the run manifest, the profile manifests'
   environment blocks AND the field manifest (whose reduced global
   series runs the standard series checks); any failing check fails
   the job.

Exit code: the doctor's (0 healthy; 1 on any failing check).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--generator", default="ring:64:2",
                    help="smoke topology")
    ap.add_argument("--rounds", type=int, default=200)
    args = ap.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    from flow_updating_tpu.cli import main as cli_main

    prof_edge = os.path.join(args.outdir, "profile_edge.json")
    rc = cli_main(["profile", "--backend", "cpu",
                   "--generator", args.generator,
                   "--rounds", "32", "--report", prof_edge])
    if rc != 0:
        print(f"obs_smoke: edge profile failed (rc={rc})",
              file=sys.stderr)
        return rc or 1

    prof_node = os.path.join(args.outdir, "profile_node.json")
    rc = cli_main(["profile", "--backend", "cpu",
                   "--generator", args.generator,
                   "--kernel", "node", "--fire-policy", "every_round",
                   "--rounds", "32", "--report", prof_node])
    if rc != 0:
        print(f"obs_smoke: node profile failed (rc={rc})",
              file=sys.stderr)
        return rc or 1

    run_manifest = os.path.join(args.outdir, "run_telemetry.json")
    rc = cli_main(["run", "--backend", "cpu",
                   "--generator", args.generator,
                   "--fire-policy", "every_round",
                   "--rounds", str(args.rounds),
                   "--telemetry", "full", "--report", run_manifest])
    if rc != 0:
        print(f"obs_smoke: telemetry run failed (rc={rc})",
              file=sys.stderr)
        return rc or 1

    # topology-resolved fields: two identical-seed recordings with blame,
    # then the diff — which must come back all-zero
    fields_a = os.path.join(args.outdir, "fields_a.json")
    fields_b = os.path.join(args.outdir, "fields_b.json")
    # stride must divide the (user-overridable) round count
    stride = next(s for s in (4, 2, 1) if args.rounds % s == 0)
    inspect_base = ["inspect", "--backend", "cpu",
                    "--generator", args.generator,
                    "--fire-policy", "every_round",
                    "--rounds", str(args.rounds),
                    "--fields", "full", "--field-stride", str(stride)]
    for path in (fields_a, fields_b):
        rc = cli_main(inspect_base + ["--blame", "--report", path])
        if rc != 0:
            print(f"obs_smoke: field recording failed (rc={rc})",
                  file=sys.stderr)
            return rc or 1
    diff_out = os.path.join(args.outdir, "fields_diff.json")
    rc = cli_main(["inspect", "--diff", fields_a, fields_b,
                   "-o", diff_out])
    if rc != 0:
        print(f"obs_smoke: field diff failed (rc={rc})", file=sys.stderr)
        return rc or 1
    with open(diff_out) as f:
        diff = json.load(f)
    if not diff.get("identical"):
        print("obs_smoke: identical-seed field runs diff nonzero: "
              f"max_abs_delta={diff.get('max_abs_delta')}",
              file=sys.stderr)
        return 1

    return cli_main(["doctor", run_manifest, prof_edge, prof_node,
                     fields_a])


if __name__ == "__main__":
    sys.exit(main())
