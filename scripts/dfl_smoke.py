#!/usr/bin/env python
"""CI DFL model-scale smoke: the feature-sharded, pipelined gossip
stack end to end on the 2-device virtual CPU mesh.

For each smoke topology (an ER gossip graph and the planted-partition
community graph — one convergence-vs-bytes curve per topology lands in
the manifest):

1. **Chunked == monolithic bit-parity.**  A ``c = D`` chunked run must
   be BIT-identical to the plain vector run (the degenerate one-chunk
   pass), and a ``c = 64`` chunked run must be bit-identical PER CHUNK
   to a monolithic run on that feature block — the pipelined schedule
   re-times the traffic, it never changes a single bit of any lane.
2. **Feature-sharded == single-device bit-parity.**  The same payload
   run with the feature axis sharded over the 2-device mesh
   (parallel/feature.py) must concatenate to the single-device run
   bit-for-bit (the control plane is replicated, the lanes are
   independent).
3. **Per-feature mass conservation.**  After the chunked run (drop>0
   included) the per-feature ledger-form residual must sit within the
   float tolerance — the paper's conservation invariant, per feature,
   per chunk.
4. **Convergence-vs-bytes curve.**  One telemetry row per full model
   stream (pass) of the chunked schedule — RMSE + per-feature mass
   residual against cumulative wire bytes (the arXiv:2506.10607
   bytes-per-accuracy measurement) — embedded in a
   ``flow-updating-run-report/v1`` manifest under the standard
   ``telemetry`` key, then audited by ``doctor`` (exit 1 on any
   failing health check).

Exit code: 0 when every assert and the doctor pass; 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FEATURE_SHARDS = 2
D = 256
CHUNK = 64

# the 2-device mesh must exist before jax initializes
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        f"{_flags} --xla_force_host_platform_device_count="
        f"{FEATURE_SHARDS}").strip()


def _fail(msg: str) -> int:
    print(f"dfl_smoke: FAIL — {msg}", file=sys.stderr)
    return 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    ap.add_argument("--rounds", type=int, default=48,
                    help="underlying rounds per chunk for the parity "
                         "runs (the curve runs 8 passes)")
    args = ap.parse_args()
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import numpy as np

    if len(jax.devices()) < FEATURE_SHARDS:
        return _fail(f"need {FEATURE_SHARDS} devices, have "
                     f"{len(jax.devices())} (jax initialized before the "
                     "device-count flag?)")

    from flow_updating_tpu.models import rounds as R
    from flow_updating_tpu.models.config import RoundConfig
    from flow_updating_tpu.models.state import init_state
    from flow_updating_tpu.obs.profile import payload_bytes_per_round
    from flow_updating_tpu.obs.report import build_manifest, write_report
    from flow_updating_tpu.obs.telemetry import (
        TelemetrySeries,
        TelemetrySpec,
    )
    from flow_updating_tpu.parallel import feature as F
    from flow_updating_tpu.topology.generators import community, erdos_renyi

    topologies = {
        "er": erdos_renyi(96, avg_degree=6.0, seed=0),
        "community": community(96, c=4, seed=0),
    }
    cfg = RoundConfig.fast(variant="collectall", kernel="edge")
    # short timeout so drop-orphaned edges refire (and heal) quickly
    cfg_drop = RoundConfig.reference(variant="collectall", kernel="edge",
                                     drop_rate=0.2, timeout=8)
    cfg_heal = RoundConfig.reference(variant="collectall", kernel="edge",
                                     timeout=8)
    mesh = F.feature_mesh(FEATURE_SHARDS)
    rng = np.random.default_rng(0)
    curves = {}
    n_chunks = D // CHUNK

    for name, topo in topologies.items():
        ta = topo.device_arrays()
        vals = rng.normal(size=(topo.num_nodes, D))
        rounds = args.rounds

        # 1a: c = D degenerates to the plain vector run, bit-for-bit
        ref = R.run_rounds(init_state(topo, cfg, values=vals), ta, cfg,
                           num_rounds=rounds)
        cs1 = R.run_rounds_chunked(
            R.init_chunked_state(topo, cfg, D, vals), ta, cfg,
            num_rounds=rounds)
        if not np.array_equal(np.asarray(R._chunk_flat(cs1.flow)),
                              np.asarray(ref.flow)):
            return _fail(f"{name}: c=D chunked run != monolithic run")

        # 1b: c = 64 is bit-identical per chunk to the per-block runs
        csc = R.run_rounds_chunked(
            R.init_chunked_state(topo, cfg, CHUNK, vals), ta, cfg,
            num_rounds=rounds * n_chunks)
        for b in range(n_chunks):
            blk = R.run_rounds(
                init_state(topo, cfg,
                           values=vals[:, b * CHUNK:(b + 1) * CHUNK]),
                ta, cfg, num_rounds=rounds)
            if not np.array_equal(np.asarray(csc.flow[b]),
                                  np.asarray(blk.flow)):
                return _fail(f"{name}: chunk {b} != its monolithic "
                             "block run")

        # 2: feature-sharded == single-device, bit-for-bit
        st = F.place_feature_state(init_state(topo, cfg, values=vals),
                                   mesh)
        out = F.run_rounds_feature(st, ta, cfg, rounds, mesh)
        if not np.array_equal(np.asarray(out.flow), np.asarray(ref.flow)):
            return _fail(f"{name}: feature-sharded run != single-device")

        # 3: per-feature mass conservation under drop>0 — the paper's
        # self-healing story under the doctor's accounting: the faithful
        # asynchronous dynamics never fully quiesce (there are ALWAYS
        # sent-but-undelivered messages carrying mass), so the residual
        # is judged against the standard in-flight allowance — factor x
        # worst per-node error x active nodes (obs/health.py, "mid-run
        # in-flight mass is NOT a leak") — after a drop-free healing
        # tail shrinks that error.
        csd = R.run_rounds_chunked(
            R.init_chunked_state(topo, cfg_drop, CHUNK, vals, seed=3),
            ta, cfg_drop, num_rounds=rounds * n_chunks)
        heal = R.run_rounds_chunked(csd, ta, cfg_heal,
                                    num_rounds=4 * rounds * n_chunks)
        est = np.asarray(R.chunked_node_estimates(heal, ta))
        mean_d = np.asarray(vals).mean(axis=0)
        max_abs_err = float(np.abs(est - mean_d).max())
        residual = np.abs(est.sum(axis=0) - np.asarray(vals).sum(axis=0))
        allowance = 2.0 * max_abs_err * topo.num_nodes \
            + 64 * np.finfo(np.float32).eps * float(
                np.abs(vals).sum(axis=0).max())
        if residual.max() > allowance:
            return _fail(f"{name}: per-feature mass residual "
                         f"{residual.max():.3e} exceeds the in-flight "
                         f"allowance {allowance:.3e}")
        # and the healing must actually shrink the error (self-healing,
        # not divergence): the healed per-node error must be far inside
        # the payload scale
        if max_abs_err > 0.5:
            return _fail(f"{name}: healed per-node error {max_abs_err} "
                         "did not contract (self-healing broken?)")

        # 4: convergence-vs-bytes curve — one telemetry row per pass
        # 'active' feeds the doctor's in-flight mass allowance (factor x
        # worst error x active nodes) — without it a mid-stream residual
        # reads as a leak
        spec = TelemetrySpec.parse("rmse,max_abs_err,mass_residual,active")
        cs0 = R.init_chunked_state(topo, cfg, CHUNK, vals)
        mean = np.asarray(vals).mean(axis=0)
        _, series = R.run_rounds_chunked_telemetry(
            cs0, ta, cfg, num_rounds=8 * n_chunks, spec=spec,
            true_mean=mean)
        series = {k: np.asarray(v) for k, v in series.items()}
        bytes_per_pass = payload_bytes_per_round(
            topo.num_edges, D, chunk=CHUNK,
            dtype_bytes=4)["bytes_per_model_stream"]
        curves[name] = {
            "topology": name,
            "nodes": topo.num_nodes,
            "directed_edges": topo.num_edges,
            "features": D,
            "chunk": CHUNK,
            "bytes_per_pass": bytes_per_pass,
            "cumulative_bytes": [bytes_per_pass * (i + 1)
                                 for i in range(len(series["rmse"]))],
            "rmse": [float(x) for x in series["rmse"]],
            "max_mass_residual": [
                float(np.abs(x).max())
                for x in series["mass_residual"]],
        }
        tser = TelemetrySeries(
            {"t": series["t"], "rmse": series["rmse"],
             "max_abs_err": series["max_abs_err"],
             "mass_residual": series["mass_residual"],
             "active": series["active"]})
        manifest = build_manifest(
            argv=sys.argv[1:], config=cfg, topo=topo,
            report={
                "mode": "dfl_smoke",
                "features": D, "chunk": CHUNK,
                "feature_shards": FEATURE_SHARDS,
                "rounds": int(series["t"][-1]),
                "convergence_vs_bytes": curves[name],
                "final_rmse": curves[name]["rmse"][-1],
                "true_mean_mean": float(mean.mean()),
            },
            telemetry=tser)
        path = os.path.join(args.outdir, f"dfl_{name}_report.json")
        write_report(path, manifest)
        print(f"dfl_smoke: {name}: parity OK, residual "
              f"{residual.max():.3e}, final rmse "
              f"{curves[name]['rmse'][-1]:.3e} after "
              f"{curves[name]['cumulative_bytes'][-1]} B -> {path}")

        # doctor-audit the manifest (any failing check fails the smoke)
        from flow_updating_tpu.cli import main as cli_main

        rc = cli_main(["doctor", path])
        if rc != 0:
            return _fail(f"{name}: doctor rejected {path} (rc={rc})")

    # 5: the bytes-efficiency regression gate, cross-machine stable
    # because it is a SAME-machine rate ratio (the scaling smoke's
    # per-chip-efficiency discipline): a D=256 payload streamed in
    # anchor-width chunks must keep >= 30% of the D=64 monolithic round
    # rate.  The recorded CPU-proxy figure is ~90% (dfl_d4096,
    # BASELINE_MEASURED.json); 30% is the collapse detector — the
    # pre-redesign chunk rotation (full-ledger copies per visit) sat at
    # ~3%, an order below the floor.
    import time

    topo = topologies["er"]
    ta = topo.device_arrays()
    vals = rng.normal(size=(topo.num_nodes, D))
    ref_state = init_state(topo, cfg, values=vals[:, :CHUNK])
    cs_perf = R.init_chunked_state(topo, cfg, CHUNK, vals)
    rpv = 16
    per_pass = n_chunks * rpv

    def rate(fn, r):
        fn(r)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            fn(r)
            best = max(best, r / (time.perf_counter() - t0))
        return best

    r_anchor = rate(lambda r: jax.block_until_ready(
        R.run_rounds(ref_state, ta, cfg, num_rounds=r).flow), 512)
    r_chunk = rate(lambda r: jax.block_until_ready(
        R.run_rounds_chunked(cs_perf, ta, cfg, num_rounds=r,
                             rounds_per_visit=rpv).flow), 4 * per_pass)
    eff = r_chunk / r_anchor
    print(f"dfl_smoke: efficiency gate: chunked {r_chunk:.1f} r/s vs "
          f"anchor {r_anchor:.1f} r/s -> {100 * eff:.1f}%")
    if eff < 0.30:
        return _fail(f"bytes-efficiency {100 * eff:.1f}% below the 30% "
                     "collapse floor (chunk rotation regressed?)")

    print(json.dumps({"ok": True,
                      "topologies": list(topologies),
                      "features": D, "chunk": CHUNK,
                      "feature_shards": FEATURE_SHARDS,
                      "efficiency_vs_anchor": round(eff, 3)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
