#!/usr/bin/env python
"""CI perf-lens smoke: calibration + roofline reports + measured timeline.

Runs the perf lens (docs/OBSERVABILITY.md; obs/roofline.py +
obs/timeline.py) end to end on CPU and leaves the manifests in
``--outdir`` (the tier1 workflow uploads them as build artifacts):

1. **calibration** — the CPU-proxy hardware model is force-probed
   (STREAM triad + chained FMA) with the record persisted into the
   outdir, so the artifact shows exactly what ceiling CI reconciled
   against;
2. **roofline reports** — ``profile --roofline --report`` across THREE
   dispatch modes (edge, node, 2-shard halo on the virtual CPU mesh):
   every manifest must carry a ``flow-updating-perf-lens/v1`` block
   whose ``roofline_frac`` lands in (0, 1];
3. **measured timeline** — the halo run captures a real
   ``jax.profiler`` device trace (``--trace-dir``) and its overlap
   ratio must be MEASURED from the timeline slices (``wire_ops > 0``,
   a numeric ``overlap_ratio_measured``, source ``device-trace``) —
   not just inferred from the three-schedule wall-clock arithmetic;
4. **doctor gates** — every manifest must pass ``doctor --strict``
   (``roofline_sane`` + ``roofline_floor`` among the clauses), and the
   NEGATIVE control — the same manifest with a frac forged above 1 —
   must FAIL it (a gate that cannot fail is not a gate).

Exit code: 0 healthy; 1 on any failed step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fail(msg: str) -> int:
    print(f"perf_lens_smoke: {msg}", file=sys.stderr)
    return 1


def _check_lens(path: str) -> str | None:
    """The in-script assert on one manifest's perf-lens block; returns
    an error string or None."""
    from flow_updating_tpu.obs.report import PERF_LENS_SCHEMA

    with open(path) as f:
        manifest = json.load(f)
    lens = manifest.get("perf_lens")
    if not isinstance(lens, dict):
        return f"{path}: no perf_lens block"
    if lens.get("schema") != PERF_LENS_SCHEMA:
        return f"{path}: wrong schema {lens.get('schema')!r}"
    fracs = {p.get("mode"): p.get("roofline_frac")
             for p in lens.get("programs") or []}
    if not fracs:
        return f"{path}: perf_lens block carries no programs"
    for mode, frac in fracs.items():
        if not isinstance(frac, (int, float)) or not 0.0 < frac <= 1.0:
            return f"{path}: mode {mode!r} frac {frac!r} outside (0, 1]"
    print(f"perf_lens_smoke: {os.path.basename(path)} fracs "
          + ", ".join(f"{m}={f:g}" for m, f in fracs.items()))
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", default="obs-artifacts",
                    help="manifest output directory (uploaded by CI)")
    args = ap.parse_args()
    os.makedirs(args.outdir, exist_ok=True)

    # 1. calibrate the CPU proxy with the record IN the artifact dir —
    # the probe must produce positive ceilings and persist its record
    cache = os.path.join(args.outdir, "roofline_cpu.json")
    os.environ["FLOW_UPDATING_ROOFLINE_CACHE"] = cache
    from flow_updating_tpu.obs import roofline

    model = roofline.calibrate_cpu(force=True)
    if model.hbm_gbps <= 0 or model.vpu_gflops <= 0:
        return _fail(f"degenerate calibration: {model.to_dict()}")
    if not os.path.exists(cache):
        return _fail("calibration record did not persist")
    print(f"perf_lens_smoke: calibrated {model.name}: "
          f"{model.hbm_gbps:.1f} GB/s, {model.vpu_gflops:.1f} GFLOP/s "
          f"({model.notes})")

    # 2. roofline reports across three dispatch modes; the halo run
    # also captures the device timeline for the measured overlap ratio.
    # Each profile runs in a CHILD process: the virtual 2-device mesh
    # (--shards 2) needs its host-device count settled before jax
    # initializes, which one shared process cannot re-do per run.
    import subprocess

    trace_dir = os.path.join(args.outdir, "perf_lens_trace")
    runs = {
        "perf_lens_edge.json": [
            "profile", "--backend", "cpu", "--generator", "ring:256:2",
            "--rounds", "64", "--roofline"],
        "perf_lens_node.json": [
            "profile", "--backend", "cpu", "--generator",
            "erdos_renyi:2048", "--kernel", "node", "--fire-policy",
            "every_round", "--rounds", "64", "--roofline"],
        "perf_lens_halo.json": [
            "profile", "--backend", "cpu", "--generator",
            "erdos_renyi:512", "--shards", "2", "--multichip", "halo",
            "--halo", "overlap", "--rounds", "8", "--roofline",
            "--trace-dir", trace_dir],
    }
    manifests = []
    for name, argv in runs.items():
        path = os.path.join(args.outdir, name)
        proc = subprocess.run(
            [sys.executable, "-m", "flow_updating_tpu",
             *argv, "--report", path],
            cwd=REPO, env=dict(os.environ), capture_output=True,
            text=True)
        if proc.returncode != 0:
            print(proc.stdout[-2000:], file=sys.stderr)
            print(proc.stderr[-2000:], file=sys.stderr)
            return _fail(f"{name}: profile failed "
                         f"(rc={proc.returncode})")
        err = _check_lens(path)
        if err:
            return _fail(err)
        manifests.append(path)

    # 3. the halo manifest's overlap ratio must be MEASURED from the
    # captured device timeline, not only inferred from wall clocks
    with open(manifests[-1]) as f:
        halo = json.load(f)
    overlap = (halo.get("profile") or {}).get("overlap") or {}
    measured = overlap.get("measured") or {}
    if measured.get("error"):
        return _fail(f"trace capture errored: {measured['error']}")
    if not isinstance(measured.get("wire_ops"), int) \
            or measured["wire_ops"] <= 0:
        return _fail(f"no wire slices in the captured timeline: "
                     f"{measured}")
    ratio = measured.get("overlap_ratio_measured")
    if not isinstance(ratio, (int, float)):
        return _fail(f"overlap_ratio_measured is not numeric: {ratio!r}")
    if overlap.get("overlap_ratio_source") != "device-trace":
        return _fail("overlap ratio was not sourced from the device "
                     f"trace: {overlap.get('overlap_ratio_source')!r}")
    print(f"perf_lens_smoke: measured overlap_ratio={ratio:g} from "
          f"{measured['wire_ops']} wire / {measured['compute_ops']} "
          f"compute slices on {measured['lanes']} lanes "
          f"(inferred three-schedule ratio: "
          f"{overlap.get('overlap_ratio')})")

    # 4a. every manifest passes the strict doctor (roofline_sane +
    # roofline_floor among the judged clauses)
    from flow_updating_tpu.cli import main as cli_main

    rc = cli_main(["doctor", "--strict", *manifests])
    if rc != 0:
        return _fail(f"doctor --strict failed on honest manifests "
                     f"(rc={rc})")

    # 4b. the NEGATIVE control: forge a frac above 1 — the physical
    # bound — and the same gate must FAIL
    with open(manifests[0]) as f:
        forged = json.load(f)
    prog = forged["perf_lens"]["programs"][0]
    prog["roofline_frac"] = 1.5
    prog["measured_rounds_per_sec"] = (
        1.5 * prog["ceiling_rounds_per_sec"])
    neg = os.path.join(args.outdir, "perf_lens_negative_control.json")
    with open(neg, "w") as f:
        json.dump(forged, f, indent=1)
    rc = cli_main(["doctor", "--strict", neg])
    if rc == 0:
        return _fail("NEGATIVE CONTROL PASSED: doctor accepted a "
                     "roofline_frac of 1.5 — the roofline_sane gate "
                     "cannot fail")
    print("perf_lens_smoke: negative control correctly failed "
          f"(rc={rc})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
