#!/usr/bin/env python
"""TPU data-movement microbenchmarks + Mosaic gather support probes.

Runs on the ambient backend (intended: the real TPU).  Three sections:

1. ``--probe-mosaic``: which gather forms Mosaic/Pallas actually compiles
   (round-3 findings, reproduced): arbitrary ``x_ref[idx]`` int indexing
   is rejected ("Cannot do int indexing on TPU"); ``take_along_axis`` is
   supported only as ``tpu.dynamic_gather`` — axis=0 at (8,128) tiles
   only, axis=1 (lane gather) at (S,128) for any S but lane dim exactly
   128.
2. ``--spmv``: per-round cost of the node kernel's neighbor-sum paths
   (xla gather vs benes permutation network) at a chosen fat-tree scale,
   measured with the R-vs-2R difference (tunnel launch overhead cancels,
   bench.make_runner closures).
3. ``--passes``: raw cost of one roll+select pass and one swap pass at
   a given power-of-two size — the unit cost model behind the Beneš
   design (BENCH_NOTES.md accounting).

Each section prints one JSON line; safe to run sections independently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def probe_mosaic() -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    results = {}

    def try_case(name, build):
        try:
            build()
            results[name] = "ok"
        except Exception as e:
            results[name] = f"{type(e).__name__}: {str(e).splitlines()[0][:120]}"

    def int_indexing():
        def kern(x_ref, i_ref, o_ref):
            o_ref[...] = x_ref[i_ref[...]]

        x = jnp.arange(1024.0)
        i = jnp.zeros((8, 128), jnp.int32)
        pl.pallas_call(
            kern,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        )(x, i).block_until_ready()

    try_case("x_ref[int_idx]", int_indexing)

    rng = np.random.default_rng(0)
    for axis in (0, 1):
        for shape in ((8, 128), (1024, 128), (8192, 128), (256, 512)):
            def tal(axis=axis, shape=shape):
                def kern(x_ref, i_ref, o_ref):
                    o_ref[...] = jnp.take_along_axis(
                        x_ref[...], i_ref[...], axis=axis
                    )

                x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
                i = jnp.asarray(rng.integers(
                    0, shape[axis], size=shape).astype(np.int32))
                out = pl.pallas_call(
                    kern,
                    in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
                    out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                    out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
                )(x, i)
                ref = np.take_along_axis(
                    np.asarray(x), np.asarray(i), axis=axis)
                assert np.array_equal(np.asarray(out), ref), "wrong results"

            try_case(f"take_along_axis[axis={axis},{shape}]", tal)
    return {"mosaic": results}


def spmv(k: int) -> dict:
    """xla-vs-benes node-kernel comparison via bench.measure_tpu (inherits
    the adaptive R-vs-2R timing AND the tunnel launch-time cap)."""
    from bench import measure_tpu
    from flow_updating_tpu import native
    from flow_updating_tpu.topology.generators import fat_tree

    import jax

    topo = fat_tree(k, seed=0)
    out = {"k": k, "nodes": topo.num_nodes, "edges": topo.num_edges,
           "platform": jax.devices()[0].platform}
    variants = ["xla", "structured"]
    if native.available():
        variants += ["benes", "benes_fused"]
    else:
        out["benes"] = {"error": "native benes router unavailable; "
                                 "pure-Python routing takes hours — skipped"}
    for spmv_name in variants:
        out[spmv_name] = {
            key: val for key, val in measure_tpu(
                topo, 32, kernel="node", spmv=spmv_name
            ).items()
            if key in ("rounds_per_sec", "per_round_s", "plan_s",
                       "compile_s", "rounds", "rmse_after")
        }
    return out


def passes(log2n: int) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    n = 1 << log2n
    d = min(1024, n // 2)  # stage distance; small n still reshapes cleanly
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=n).astype(np.float32))
    mask = jnp.asarray(rng.integers(0, 2, size=n).astype(bool))

    def chain(body):
        import functools

        @functools.partial(jax.jit, static_argnames="k")
        def f(x, k):
            return jax.lax.fori_loop(0, k, lambda _, v: body(v), x)

        def run(k):
            np.asarray(f(x, k)[:2])

        run(4)
        run(12)
        t4 = time.perf_counter(); run(4); t4 = time.perf_counter() - t4
        t12 = time.perf_counter(); run(12); t12 = time.perf_counter() - t12
        return (t12 - t4) / 8

    roll = chain(lambda v: jnp.where(mask, jnp.roll(v, d), v))
    swap = chain(lambda v: jnp.where(
        mask, jnp.flip(v.reshape(-1, 2, d), axis=1).reshape(n), v))
    return {
        "n": n,
        "roll_select_pass_ms": round(roll * 1e3, 4),
        "swap_select_pass_ms": round(swap * 1e3, 4),
        "platform": jax.devices()[0].platform,
    }


def configs() -> dict:
    """The non-fat-tree BASELINE.json configs, TPU-timed: ER-10k
    (collect-all fast node kernel + fast PAIRWISE edge kernel, the
    'pairwise Flow-Updating, Erdős–Rényi 10k nodes' config) and BA-100k
    collect-all (the degree-skewed scatter config).  Fat-tree rows live
    in the --spmv tables; this closes the configs' TPU coverage.

    Each row carries its own like-for-like DES baseline (fast rows:
    timeout=1, the same per-tick algorithmic work as the fast kernels;
    faithful rows: timeout=50, the reference's own dynamics — VERDICT
    r4 item 2 'rows with their own DES baselines and vs_baseline').
    The DES runs on the HOST CPU, so measuring it here costs no tunnel
    time; record_baseline keeps the fastest mean across sessions."""
    from bench import (
        baseline_entry,
        measure_des_baseline,
        measure_tpu,
        record_baseline,
        recorded_baseline,
    )
    from flow_updating_tpu import native
    from flow_updating_tpu.topology.generators import (
        barabasi_albert,
        erdos_renyi,
    )

    import jax

    out = {"platform": jax.devices()[0].platform, "rows": []}
    fused = native.available()

    er = erdos_renyi(10_000, avg_degree=8.0, seed=0)
    ba = barabasi_albert(100_000, m=4, seed=0)
    cases = [
        ("er10k_collectall_node", er, "er10k_collectall",
         dict(kernel="node", spmv="benes_fused" if fused else "xla")),
        ("er10k_pairwise_edge_fast", er, "er10k_pairwise",
         dict(kernel="edge", variant="pairwise",
              segment="benes_fused" if fused else "auto")),
        ("ba100k_collectall_node", ba, "ba100k_collectall",
         dict(kernel="node", spmv="benes_fused" if fused else "xla")),
    ]
    if fused:
        # the xla-gather comparison row is only informative when the
        # main BA row actually ran the fused path (otherwise identical)
        cases.append(("ba100k_collectall_node_xla", ba, "ba100k_collectall",
                      dict(kernel="node", spmv="xla")))
    ref_platform = "/root/reference/platforms/small_platform.xml"
    ref_actors = "/root/reference/actors.xml"
    if os.path.exists(ref_platform) and os.path.exists(ref_actors):
        # BASELINE.json config 4: faithful pairwise with per-link latency
        # from the reference platform XML (async / time-warped rounds).
        # 6 actors — the row exists for config-table completeness; the
        # scale story lives in the fidelity tests (test_dynamics_parity,
        # test_lmm)
        from flow_updating_tpu.topology.deployment import load_deployment
        from flow_updating_tpu.topology.platform import load_platform

        warped = load_deployment(ref_actors).to_topology(
            load_platform(ref_platform), latency_scale=100.0)
        cases.append(
            ("smallplatform_pairwise_warped", warped,
             "smallplatform_pairwise_warped",
             dict(kernel="edge", variant="pairwise",
                  fire_policy="reference")))
    measured_keys = set()
    for name, topo, base_key, kw in cases:
        row = {"name": name, "nodes": topo.num_nodes,
               "edges": topo.num_edges, "baseline_key": base_key, **kw}
        try:
            row.update(measure_tpu(topo, 64, **kw))
        except Exception as exc:  # keep earlier rows
            row["error"] = f"{type(exc).__name__}: {exc}"[:300]
        if base_key not in measured_keys:
            measured_keys.add(base_key)
            variant = kw.get("variant", "collectall")
            faithful = kw.get("fire_policy", "fast") == "reference"
            # faithful rows divide by a faithful DES (timeout=50, the
            # reference default); fast rows by timeout=1 (same per-tick
            # work as the fast kernels).  Pairwise DES ticks are ~4x
            # faster than collect-all's and visit-order noise is larger:
            # longer runs concentrate the mean so keep-fastest cannot
            # ratchet on scheduler luck; the 6-node warped config is
            # nearly free, so it gets a long run outright.
            if topo.num_nodes <= 100:
                ticks = 2000
            elif variant == "pairwise":
                ticks = 30
            else:
                ticks = 10
            des = measure_des_baseline(topo, ticks=ticks, repeats=3,
                                       timeout=50 if faithful else 1,
                                       variant=variant)
            if des is not None:
                record_baseline(base_key, baseline_entry(topo, des))
        base = recorded_baseline(base_key)
        row["baseline_rounds_per_sec"] = base
        if base and "rounds_per_sec" in row:
            row["vs_baseline"] = round(row["rounds_per_sec"] / base, 2)
        out["rows"].append(row)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe-mosaic", action="store_true")
    ap.add_argument("--spmv", type=int, metavar="K",
                    help="fat-tree arity for the spmv comparison")
    ap.add_argument("--passes", type=int, metavar="LOG2N",
                    help="log2 size for the unit-pass timing")
    ap.add_argument("--configs", action="store_true",
                    help="ER-10k / BA-100k BASELINE.json config rows")
    args = ap.parse_args()
    ran = False
    if args.probe_mosaic:
        print(json.dumps(probe_mosaic()))
        ran = True
    if args.spmv:
        print(json.dumps(spmv(args.spmv)))
        ran = True
    if args.passes:
        print(json.dumps(passes(args.passes)))
        ran = True
    if args.configs:
        print(json.dumps(configs()))
        ran = True
    if not ran:
        print(json.dumps({"error": "pick --probe-mosaic / --spmv K / "
                                   "--passes LOG2N / --configs"}))


if __name__ == "__main__":
    main()
