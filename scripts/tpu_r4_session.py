#!/usr/bin/env python
"""One-contact TPU measurement session for round 4.

The axon tunnel wedges for hours after any killed TPU process, so a
successful probe must be exploited immediately and in strict priority
order, banking each result to a repo JSON artifact the moment it exists
(BENCH_NOTES.md runbook; VERDICT r3 items 1, 2, 4):

  1. microbench --spmv 96   — cheap canary: catches a Mosaic compile
     problem in the fused kernels at 233k nodes; also records the
     plan_s/compile_s split (item 4).
  2. microbench --spmv 160  — the headline scale: xla vs benes vs
     benes_fused at 1.056M nodes.
  3. bench.py               — the full headline with --spmv auto
     (vs_baseline against the baseline of record).
  4. profile_round --k 160  — per-round cost attribution (spmv vs
     elementwise floor) for the roofline-gap work (item 2).
  5. microbench --spmv 40   — the small-scale compile-cost row
     completing the k=40/96/160 compile-time table.

Every step is a *sequential* subprocess with NO timeout — timeout-killing
a mid-compile TPU process is what wedges the tunnel (memory: tunnel
discipline).  The tunnel itself kills >60 s on-device executions; all
launch sizes here respect bench.py's MAX_LAUNCH_S.  A step that exits
nonzero is recorded and the session continues (transient compile-helper
SIGKILLs are common — step 1 is retried once).

Usage: python scripts/tpu_r4_session.py [--skip-probe]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def _session_env() -> dict:
    """Child env: persistent XLA compilation cache shared across the
    session's processes — the k=160 fused-path compile is paid once, not
    per step (the routed-plan disk cache covers the host side the same
    way)."""
    env = dict(os.environ)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/flow_updating_tpu/xla"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    return env


def _run(cmd: list[str], log_name: str) -> tuple[int, str]:
    """Run to completion (NO timeout — see module doc), tee to a log."""
    log_path = os.path.join(REPO, f"_tpu_session_{log_name}.log")
    t0 = time.time()
    with open(log_path, "w") as lf:
        p = subprocess.run(cmd, cwd=REPO, stdout=lf,
                           stderr=subprocess.STDOUT, env=_session_env())
    out = open(log_path).read()
    print(f"[{log_name}] rc={p.returncode} {time.time()-t0:.0f}s "
          f"({len(out)}B log)", flush=True)
    return p.returncode, out


def _json_lines(text: str) -> list[dict]:
    rows = []
    for ln in text.splitlines():
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
    return rows


def _bank(path: str, payload) -> None:
    with open(os.path.join(REPO, path), "w") as f:
        json.dump(payload, f, indent=1)
    print(f"banked {path}", flush=True)


def probe() -> bool:
    # one probe implementation for the whole repo: bench.py's subprocess
    # probe (290 s budget, wedge-safe, reads the final stdout token)
    sys.path.insert(0, REPO)
    from bench import _probe_tpu

    status, detail = _probe_tpu()
    print(f"probe: {status} ({detail})", flush=True)
    return status == "ok"


ALL_STEPS = ("micro96", "micro160", "bench", "profile160", "micro40",
             "edge96", "edge96_fused", "megascale", "configs")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-probe", action="store_true")
    ap.add_argument("--steps", default=",".join(ALL_STEPS),
                    help="comma-separated subset to run (a follow-up "
                         "contact after a mid-session wedge should skip "
                         "the already-banked steps, e.g. "
                         "--steps bench,profile160,micro40,edge96)")
    args = ap.parse_args()
    steps = [s.strip() for s in args.steps.split(",") if s.strip()]
    unknown = set(steps) - set(ALL_STEPS)
    if unknown:
        ap.error(f"unknown steps {sorted(unknown)}; have {ALL_STEPS}")

    if not args.skip_probe and not probe():
        return 3

    session: dict = {"started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                  time.gmtime()),
                     "steps": {}}
    # a follow-up session merges into the already-banked artifact rather
    # than discarding the earlier contact's measurements
    micro_path = os.path.join(REPO, "MICROBENCH_TPU_r4.json")
    if os.path.exists(micro_path):
        try:
            with open(micro_path) as f:
                banked = json.load(f)
            if isinstance(banked, dict):
                session["steps"].update(banked)
        except (OSError, json.JSONDecodeError):
            pass

    def _keep(step: str, record: dict, good: bool) -> None:
        """Bank a step's result — but never let a failed or degraded
        re-run clobber a previously banked success (the artifact carries
        the round's verified numbers of record; see
        bench._live_tpu_of_record)."""
        prior = session["steps"].get(step)
        if good or not prior:
            session["steps"][step] = record
        _bank("MICROBENCH_TPU_r4.json", session["steps"])

    def _tpu_rows(rc: int, rows: list) -> bool:
        """Microbench goodness: clean exit AND rows measured on the TPU
        — a CPU-run microbench (silent backend fallback, --skip-probe
        misuse) must not displace banked TPU rows."""
        return rc == 0 and bool(rows) and all(
            r.get("platform") == "tpu" for r in rows)

    # -- 1. canary at k=96 (retry once: transient helper SIGKILLs) -------
    if "micro96" in steps:
        for attempt in (1, 2):
            rc, out = _run([PY, "scripts/tpu_microbench.py", "--spmv", "96"],
                           f"micro96_a{attempt}")
            rows = _json_lines(out)
            if rc == 0 and rows:
                break
        _keep("micro96", {"rc": rc, "rows": rows}, _tpu_rows(rc, rows))
        if rc != 0 or not rows:  # rc=0 with no rows proves nothing
            print("canary failed twice — banking what exists and stopping "
                  "before a wedged tunnel eats the session", flush=True)
            return 4

    # -- 2. headline scale k=160 ----------------------------------------
    if "micro160" in steps:
        rc, out = _run([PY, "scripts/tpu_microbench.py", "--spmv", "160"],
                       "micro160")
        rows = _json_lines(out)
        _keep("micro160", {"rc": rc, "rows": rows}, _tpu_rows(rc, rows))

    # -- 3. full headline bench -----------------------------------------
    if "bench" in steps:
        rc, out = _run([PY, "bench.py"], "bench")
        rows = _json_lines(out)
        # only bank a live TPU result under the TPU artifact name; a
        # CPU fallback (ok:false) must not shadow/claim the TPU slot
        live = bool(rows) and rows[-1].get("backend") == "tpu" \
            and bool(rows[-1].get("ok"))
        if live:
            _bank("BENCH_TPU_r4.json", rows[-1])
        _keep("bench", {"rc": rc, "result": rows[-1] if rows else None},
              live)

    # -- 4. per-round attribution ---------------------------------------
    if "profile160" in steps:
        rc, out = _run([PY, "scripts/tpu_profile_round.py", "--k", "160"],
                       "profile160")
        rows = _json_lines(out)
        good = rc == 0 and bool(rows)
        _keep("profile160", {"rc": rc, "rows": rows}, good)
        if good or not os.path.exists(os.path.join(REPO,
                                                   "PROFILE_TPU_r4.json")):
            _bank("PROFILE_TPU_r4.json", session["steps"]["profile160"])

    # -- 5. small-scale compile row -------------------------------------
    if "micro40" in steps:
        rc, out = _run([PY, "scripts/tpu_microbench.py", "--spmv", "40"],
                       "micro40")
        rows = _json_lines(out)
        _keep("micro40", {"rc": rc, "rows": rows}, _tpu_rows(rc, rows))

    # -- 6/7. faithful-path (edge kernel) secondary headlines at k=96 ---
    # full async fidelity (1 msg/round drain, FIFO, timeouts): once with
    # the default segment layout (banked r4 first contact), once with the
    # fused segment circuits — the faithful path's intended TPU layout
    # (the default 'segment' is a scatter lowering, TPU's slowest form)
    for step, extra in (("edge96", []),
                        ("edge96_fused", ["--segment", "benes_fused",
                                          "--delivery", "benes_fused"])):
        if step not in steps:
            continue
        rc, out = _run([PY, "bench.py", "--kernel", "edge", "--fire-policy",
                        "reference", "--fat-tree-k", "96", "--skip-des",
                        "--skip-convergence", *extra], step)
        rows = _json_lines(out)
        live = bool(rows) and rows[-1].get("backend") == "tpu" \
            and bool(rows[-1].get("ok"))
        _keep(step, {"rc": rc, "result": rows[-1] if rows else None}, live)

    # -- 8. mega-scale ladder (virtual fat-trees, structured stencil) ---
    # banks its own artifact progressively (MEGASCALE_TPU_r4.json) and
    # itself refuses to bank non-TPU rows (tpu_megascale.py exits 2 on a
    # CPU backend), so rc==0 here does imply TPU-measured rows
    if "megascale" in steps:
        rc, out = _run([PY, "scripts/tpu_megascale.py"], "megascale")
        _keep("megascale", {"rc": rc}, rc == 0)

    # -- 9. the non-fat-tree BASELINE.json configs (ER-10k, BA-100k) ----
    if "configs" in steps:
        rc, out = _run([PY, "scripts/tpu_microbench.py", "--configs"],
                       "configs")
        rows = _json_lines(out)
        good = rc == 0 and bool(rows) \
            and rows[-1].get("platform") == "tpu" \
            and all("error" not in r for r in rows[-1].get("rows", []))
        _keep("configs", {"rc": rc,
                          "result": rows[-1] if rows else None}, good)

    print("session complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
